"""SKY-TRACE: recompile/abort hazards in jit-reachable code.

The engine's performance contract is "zero new compiled programs in
steady state" (prefill compiles once per bucket; decode/free/cow
exactly once — ``InferenceEngine.compiled_counts`` and the
recompile-stability test pin the counts at runtime). The two ways
Python code breaks that contract are both *static* properties:

1. **Concretization**: ``int()`` / ``float()`` / ``bool()`` /
   ``.item()`` / ``.tolist()`` on a traced value. Under ``jit`` these
   either abort tracing (``TracerBoolConversionError``) or force a
   host sync; either way they do not belong in compiled code.
2. **Data-dependent Python branching**: an ``if``/``while`` whose
   condition depends on a traced value bakes the taken branch into
   the compiled program — a different value traces a DIFFERENT
   program (a new compile per distinct value, the recompile hazard).

This checker is the static complement of the runtime test: it finds
the hazard in code paths the test's workload never exercises.

Reachability: roots are functions passed to ``jax.jit(fn, ...)`` or
the engine's local ``_jit(fn, ...)`` wrapper, in ``infer/`` modules.
From each root the call graph is followed through bare-name calls,
locally-nested defs referenced by name (``jax.lax.scan(body, ...)``),
and ``alias.func`` calls resolved through this package's imports —
over every scanned module, so hazards in ``ops/`` or ``models/``
reached from an ``infer/`` entry point are found too.

Static-vs-traced, per function: ``self``/``config``/``cfg`` and
parameters that are annotated with a Python scalar type or carry a
literal default (``impl: str = 'auto'``, ``top_k: int = 0``) are
STATIC — they select the program, they don't trace. Everything else
(arrays, and locals assigned from them) is TRACED. A name used only
under ``.shape``/``.dtype``/``.ndim``, inside ``len()``/
``isinstance()``, or in an ``is (not) None`` test stays static —
those are structural, known at trace time.
"""
from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from skypilot_tpu.analysis import core
from skypilot_tpu.analysis import walker

ROOT_DIRS = ('infer/',)
_STATIC_PARAM_NAMES = frozenset(('self', 'config', 'cfg'))
_SCALAR_ANNOTATIONS = frozenset(('int', 'float', 'bool', 'str'))
_CONCRETIZERS = frozenset(('int', 'float', 'bool'))
_CONCRETIZER_METHODS = frozenset(('item', 'tolist'))
_STRUCTURAL_ATTRS = frozenset(('shape', 'dtype', 'ndim', 'size',
                               'at', 'sharding'))
_STRUCTURAL_CALLS = frozenset(('len', 'isinstance', 'getattr',
                               'hasattr', 'range', 'type'))

# Shared with the lock-flow pass — the call-graph index, import
# resolution and qualname helpers live in walker.py now. The old
# underscore names stay as aliases (tests and downstream callers use
# them as the canonical entry points).
FuncKey = walker.FuncKey
_FuncInfo = walker.FuncInfo
_index_functions = walker.index_functions
_imports = walker.module_imports
_enclosing_qualname = walker.enclosing_qualname


class TraceChecker(core.Checker):
    code = 'SKY-TRACE'
    title = ('no concretization or data-dependent branching in '
             'jit-reachable code')

    def check(self, files: Sequence[core.SourceFile],
              ctx: core.RunContext) -> Iterable[core.Finding]:
        index = _index_functions(files)
        by_rel = {s.rel: s for s in files}
        roots = self._find_roots(files)
        reachable: List[_FuncInfo] = []
        seen: Set[FuncKey] = set()
        queue = [k for k in roots if k not in seen]
        while queue:
            key = queue.pop()
            if key in seen:
                continue
            seen.add(key)
            rel, qn = key
            info = index.get(rel, {}).get(qn)
            if info is None:
                continue
            reachable.append(info)
            for callee in self._callees(info, index, by_rel):
                if callee not in seen:
                    queue.append(callee)
        for info in sorted(reachable,
                           key=lambda i: (i.src.rel, i.node.lineno)):
            yield from self._check_function(info)

    # -- reachability ------------------------------------------------------
    def _find_roots(self, files: Sequence[core.SourceFile]
                    ) -> List[FuncKey]:
        roots: List[FuncKey] = []
        for src in files:
            if not any(src.rel.startswith(d) for d in ROOT_DIRS):
                continue
            for node in ast.walk(src.tree):
                if not isinstance(node, ast.Call):
                    continue
                name = walker.call_name(node)
                if name not in ('jax.jit', '_jit', 'jit'):
                    continue
                if not node.args:
                    continue
                fn = node.args[0]
                if not isinstance(fn, ast.Name):
                    continue
                qn = _enclosing_qualname(node)
                # The jitted function is defined in the enclosing
                # scope chain: try innermost-out.
                parts = qn.split('.') if qn else []
                for depth in range(len(parts), -1, -1):
                    cand = '.'.join(parts[:depth] + [fn.id])
                    roots.append((src.rel, cand))
        return roots

    def _callees(self, info: _FuncInfo,
                 index: Dict[str, Dict[str, _FuncInfo]],
                 by_rel: Dict[str, core.SourceFile]) -> List[FuncKey]:
        src = info.src
        imports = _imports(src)
        mod_funcs = index.get(src.rel, {})
        out: List[FuncKey] = []
        prefix_parts = info.qualname.split('.')
        for node in ast.walk(info.node):
            # Bare names referencing a function — covers direct calls
            # AND functions passed as arguments (lax.scan(body, ...)).
            if isinstance(node, ast.Name) and isinstance(
                    node.ctx, ast.Load):
                for depth in range(len(prefix_parts), -1, -1):
                    cand = '.'.join(prefix_parts[:depth] + [node.id])
                    if cand in mod_funcs:
                        out.append((src.rel, cand))
                        break
            elif isinstance(node, ast.Attribute):
                name = walker.dotted_name(node)
                if name is None or '.' not in name:
                    continue
                alias, func = name.split('.', 1)
                target = imports.get(alias)
                if target is None or '.' in func:
                    continue
                if func in index.get(target, {}):
                    out.append((target, func))
        return out

    # -- per-function analysis ---------------------------------------------
    @staticmethod
    def _static_params(fn: ast.AST) -> Set[str]:
        static: Set[str] = set()
        args = fn.args
        all_args = (list(args.posonlyargs) + list(args.args)
                    + list(args.kwonlyargs))
        defaults = dict(zip(
            [a.arg for a in (list(args.posonlyargs)
                             + list(args.args))[-len(args.defaults):]],
            args.defaults)) if args.defaults else {}
        for a, d in zip([a.arg for a in args.kwonlyargs],
                        args.kw_defaults):
            if d is not None:
                defaults[a] = d
        for a in all_args:
            if a.arg in _STATIC_PARAM_NAMES:
                static.add(a.arg)
                continue
            ann = a.annotation
            if (isinstance(ann, ast.Name)
                    and ann.id in _SCALAR_ANNOTATIONS):
                static.add(a.arg)
                continue
            if isinstance(ann, ast.Constant) and isinstance(
                    ann.value, str):
                # String annotation like 'int' — strip Optional[...]
                inner = ann.value.split('[')[0]
                if inner in _SCALAR_ANNOTATIONS:
                    static.add(a.arg)
                    continue
            d = defaults.get(a.arg)
            if isinstance(d, ast.Constant):
                # A literal default marks a program-selection knob
                # (impl='auto', top_k=0, interpret=None) — traced
                # array args never default to literals.
                static.add(a.arg)
        return static

    def _traced_names_in(self, expr: ast.AST,
                         traced: Set[str]) -> Set[str]:
        """Traced names ``expr`` *concretely* depends on — names used
        only structurally (.shape/len/isinstance/is-None) excluded."""
        found: Set[str] = set()

        def visit(node: ast.AST) -> None:
            if isinstance(node, ast.Attribute):
                if node.attr in _STRUCTURAL_ATTRS:
                    return
                visit(node.value)
                return
            if isinstance(node, ast.Call):
                name = walker.call_name(node)
                if name in _STRUCTURAL_CALLS:
                    return
                for child in ast.iter_child_nodes(node):
                    visit(child)
                return
            if isinstance(node, ast.Compare):
                if all(isinstance(op, (ast.Is, ast.IsNot))
                       for op in node.ops):
                    return
            if isinstance(node, ast.Name):
                if node.id in traced:
                    found.add(node.id)
                return
            for child in ast.iter_child_nodes(node):
                visit(child)

        visit(expr)
        return found

    def _check_function(self,
                        info: _FuncInfo) -> Iterable[core.Finding]:
        fn = info.node
        static = self._static_params(fn)
        all_params = {a.arg for a in (list(fn.args.posonlyargs)
                                      + list(fn.args.args)
                                      + list(fn.args.kwonlyargs))}
        if fn.args.vararg:
            all_params.add(fn.args.vararg.arg)
        if fn.args.kwarg:
            all_params.add(fn.args.kwarg.arg)
        traced: Set[str] = set(all_params - static)
        # Taint pass to a FIXPOINT, in source order, add-only: a local
        # assigned from a traced value becomes traced, transitively
        # (y = x; z = y). Monotone on purpose — a name once traced
        # stays traced even if later re-bound to a static value (the
        # over-approximation cannot oscillate and cannot silently
        # un-taint through multi-step chains or `x += 1`, whose RHS
        # alone looks static but whose result still carries x's old
        # traced value).
        assigns = [n for n in walker.walk_function_body(fn)
                   if isinstance(n, (ast.Assign, ast.AugAssign,
                                     ast.AnnAssign))
                   and n.value is not None]
        assigns.sort(key=lambda n: (n.lineno, n.col_offset))
        changed = True
        while changed:
            changed = False
            for node in assigns:
                tainted = bool(self._traced_names_in(node.value,
                                                     traced))
                if isinstance(node, ast.AugAssign) and not tainted:
                    # x += e reads x's old value too.
                    tainted = any(
                        isinstance(leaf, ast.Name)
                        and leaf.id in traced
                        for leaf in ast.walk(node.target))
                if not tainted:
                    continue
                targets = (node.targets
                           if isinstance(node, ast.Assign)
                           else [node.target])
                for t in targets:
                    for leaf in ast.walk(t):
                        if (isinstance(leaf, ast.Name)
                                and leaf.id not in traced):
                            traced.add(leaf.id)
                            changed = True
        for node in walker.walk_function_body(fn):
            yield from self._check_node(info, node, traced)

    def _check_node(self, info: _FuncInfo, node: ast.AST,
                    traced: Set[str]) -> Iterable[core.Finding]:
        src = info.src
        if isinstance(node, ast.Call):
            name = walker.call_name(node)
            if name in _CONCRETIZERS and node.args:
                deps = self._traced_names_in(node.args[0], traced)
                if deps:
                    yield core.Finding(
                        self.code, src.rel, node.lineno,
                        f'{name}() on traced value '
                        f'{"/".join(sorted(deps))} in jit-reachable '
                        f'{info.qualname} — concretization aborts '
                        f'tracing or forces a host sync')
            elif (isinstance(node.func, ast.Attribute)
                  and node.func.attr in _CONCRETIZER_METHODS):
                deps = self._traced_names_in(node.func.value, traced)
                if deps:
                    yield core.Finding(
                        self.code, src.rel, node.lineno,
                        f'.{node.func.attr}() on traced value '
                        f'{"/".join(sorted(deps))} in jit-reachable '
                        f'{info.qualname} — forces a device sync '
                        f'inside the compiled path')
        elif isinstance(node, (ast.If, ast.While, ast.IfExp)):
            deps = self._traced_names_in(node.test, traced)
            if deps:
                kind = ('while' if isinstance(node, ast.While)
                        else 'if')
                yield core.Finding(
                    self.code, src.rel, node.lineno,
                    f'data-dependent Python {kind} on traced value '
                    f'{"/".join(sorted(deps))} in jit-reachable '
                    f'{info.qualname} — bakes the branch into the '
                    f'compiled program (one recompile per distinct '
                    f'value); use jnp.where / lax.cond')
