"""SKY-REGISTRY: code↔docs catalog sync for failpoints and metrics.

Two registries drive operability and MUST NOT drift from their docs:

1. **Failpoint sites** — every ``failpoints.hit('x')`` /
   ``hit_async('x')`` call site in the package must appear in
   docs/robustness.md's "Site catalog" table, and every cataloged
   site must still exist in code. An undocumented site is a chaos
   hook nobody can find; a documented ghost site is a chaos spec that
   silently injects nothing (exactly the failure mode the failpoint
   module's loud spec errors exist to prevent).

2. **Serving metric keys** — every key emitted by the serving metric
   surfaces (``InferenceEngine.metrics`` / ``EnginePool.metrics``,
   ``PrefixCache.stats``, the infer server's ``h_metrics`` additions,
   the LB's ``lb_metrics``) must appear in docs/observability.md's
   "Serving metrics" catalog tables, and vice versa. Dashboards and
   the TTFT bench are built on these names; a renamed key is a
   silently-flatlined graph.

Doc format contract: catalog entries are markdown table rows whose
first cell is the backticked name —  ``| `site.name` | ... |`` —
inside the "### Site catalog" section (robustness.md) or the
"## Serving metrics" section (observability.md).

The doc→code direction only runs on a full-package scan (a partial
``sky-tpu lint path`` cannot see every call site, so "documented but
not found" would false-fire). Doc-side findings use the path
``docs/<file>`` so allowlist keys stay uniform.
"""
from __future__ import annotations

import ast
import os
import re
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from skypilot_tpu.analysis import core
from skypilot_tpu.analysis import walker

# Functions whose dict-literal keys / subscript-assignment keys form
# the serving-metrics namespace: (module rel path, function name).
METRIC_FUNCS: Tuple[Tuple[str, str], ...] = (
    ('infer/engine.py', 'metrics'),
    ('infer/prefix_cache.py', 'stats'),
    ('infer/sched/base.py', 'aggregate_stats'),
    ('infer/server.py', 'h_metrics'),
    ('serve/load_balancer.py', 'lb_metrics'),
)

# Functions whose string literals starting with the exposition prefix
# name Prometheus metric families (observability/prometheus.py's
# curated maps): every family must appear in docs/observability.md's
# "## Prometheus exposition" catalog, both directions — a renamed
# family is a silently-flatlined scrape.
EXPOSITION_FUNCS: Tuple[Tuple[str, str], ...] = (
    ('observability/prometheus.py', 'lb_exposition'),
    ('observability/prometheus.py', 'replica_exposition'),
    ('observability/prometheus.py', 'label_families'),
)
EXPOSITION_PREFIX = 'sky_tpu_'

_ROW_RE = re.compile(r'^\|\s*`([^`]+)`')


def _doc_section_names(docs_root: str, fname: str, heading: str
                       ) -> Optional[Tuple[Set[str], Dict[str, int]]]:
    """Backticked first-cell names of table rows inside ``heading``'s
    section. Returns (names, name->line) or None when the doc or the
    section is missing."""
    path = os.path.join(docs_root, fname)
    if not os.path.isfile(path):
        return None
    with open(path, encoding='utf-8') as f:
        lines = f.read().splitlines()
    level = heading.split(' ', 1)[0]     # '##' or '###'
    names: Set[str] = set()
    where: Dict[str, int] = {}
    in_section = False
    for i, line in enumerate(lines, 1):
        if line.strip() == heading:
            in_section = True
            continue
        if in_section and line.startswith('#'):
            hashes = line.split(' ', 1)[0]
            if len(hashes) <= len(level):
                break
        if not in_section:
            continue
        m = _ROW_RE.match(line.strip())
        if m:
            name = m.group(1)
            names.add(name)
            where.setdefault(name, i)
    if not in_section:
        return None
    return names, where


class RegistryChecker(core.Checker):
    code = 'SKY-REGISTRY'
    title = ('failpoint sites and serving-metric keys stay in sync '
             'with the docs catalogs')

    def check(self, files: Sequence[core.SourceFile],
              ctx: core.RunContext) -> Iterable[core.Finding]:
        if ctx.docs_root is None:
            return
        yield from self._check_failpoints(files, ctx)
        yield from self._check_metrics(files, ctx)
        yield from self._check_exposition(files, ctx)

    # -- failpoint sites ---------------------------------------------------
    def _failpoint_sites(self, files: Sequence[core.SourceFile]
                         ) -> List[Tuple[str, str, int]]:
        sites: List[Tuple[str, str, int]] = []
        for src in files:
            for node in ast.walk(src.tree):
                if not isinstance(node, ast.Call):
                    continue
                name = walker.call_name(node)
                if name is None:
                    continue
                leaf = name.rsplit('.', 1)[-1]
                if leaf not in ('hit', 'hit_async'):
                    continue
                if '.' in name and not name.startswith('failpoints'):
                    # someone_else.hit() — only the failpoints module
                    # (or a direct import of its functions) counts.
                    continue
                if (node.args
                        and isinstance(node.args[0], ast.Constant)
                        and isinstance(node.args[0].value, str)):
                    sites.append((node.args[0].value, src.rel,
                                  node.lineno))
        return sites

    def _check_failpoints(self, files: Sequence[core.SourceFile],
                          ctx: core.RunContext
                          ) -> Iterable[core.Finding]:
        doc = _doc_section_names(ctx.docs_root, 'robustness.md',
                                 '### Site catalog')
        if doc is None:
            if ctx.full_package:
                yield core.Finding(
                    self.code, 'docs/robustness.md', 0,
                    'failpoint "### Site catalog" section not found '
                    '— the chaos-site registry has no docs anchor')
            return
        documented, where = doc
        sites = self._failpoint_sites(files)
        for site, rel, lineno in sites:
            if site not in documented:
                yield core.Finding(
                    self.code, rel, lineno,
                    f'failpoint site {site!r} is not in '
                    f'docs/robustness.md\'s site catalog — an '
                    f'undocumented chaos hook nobody can arm')
        if ctx.full_package:
            in_code = {s for s, _, _ in sites}
            for site in sorted(documented - in_code):
                yield core.Finding(
                    self.code, 'docs/robustness.md',
                    where.get(site, 0),
                    f'cataloged failpoint site {site!r} has no '
                    f'hit()/hit_async() call site left in the '
                    f'package — a chaos spec naming it silently '
                    f'injects nothing')

    # -- serving metric keys -----------------------------------------------
    @staticmethod
    def _metric_keys(files: Sequence[core.SourceFile]
                     ) -> List[Tuple[str, str, int]]:
        by_rel = {s.rel: s for s in files}
        keys: List[Tuple[str, str, int]] = []
        for rel, fn_name in METRIC_FUNCS:
            src = by_rel.get(rel)
            if src is None:
                continue
            for node in ast.walk(src.tree):
                if not isinstance(node, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                    continue
                if node.name != fn_name:
                    continue
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Dict):
                        for k in sub.keys:
                            if (isinstance(k, ast.Constant)
                                    and isinstance(k.value, str)):
                                keys.append((k.value, rel, k.lineno))
                    elif (isinstance(sub, ast.Subscript)
                          and isinstance(sub.ctx, ast.Store)
                          and isinstance(sub.slice, ast.Constant)
                          and isinstance(sub.slice.value, str)):
                        keys.append((sub.slice.value, rel,
                                     sub.lineno))
        return keys

    def _check_metrics(self, files: Sequence[core.SourceFile],
                       ctx: core.RunContext) -> Iterable[core.Finding]:
        relevant = {rel for rel, _ in METRIC_FUNCS}
        scanned = {s.rel for s in files}
        if not relevant & scanned:
            return   # partial scan with no metric surface in it
        doc = _doc_section_names(ctx.docs_root, 'observability.md',
                                 '## Serving metrics')
        if doc is None:
            yield core.Finding(
                self.code, 'docs/observability.md', 0,
                'serving-metrics catalog ("## Serving metrics") not '
                'found in docs/observability.md')
            return
        documented, where = doc
        keys = self._metric_keys(files)
        seen: Set[Tuple[str, str]] = set()
        for key, rel, lineno in keys:
            if key in documented or (key, rel) in seen:
                continue
            seen.add((key, rel))
            yield core.Finding(
                self.code, rel, lineno,
                f'metric key {key!r} is not in '
                f'docs/observability.md\'s serving-metrics catalog '
                f'— dashboards cannot discover it')
        if ctx.full_package:
            in_code = {k for k, _, _ in keys}
            for key in sorted(documented - in_code):
                yield core.Finding(
                    self.code, 'docs/observability.md',
                    where.get(key, 0),
                    f'cataloged metric key {key!r} is no longer '
                    f'emitted by any serving metric surface — a '
                    f'dashboard graphing it has flatlined')

    # -- Prometheus exposition families --------------------------------------
    @staticmethod
    def _exposition_families(files: Sequence[core.SourceFile]
                             ) -> List[Tuple[str, str, int]]:
        """Every ``sky_tpu_*`` string literal inside the curated
        exposition maps — the family namespace a scrape sees."""
        by_rel = {s.rel: s for s in files}
        fams: List[Tuple[str, str, int]] = []
        for rel, fn_name in EXPOSITION_FUNCS:
            src = by_rel.get(rel)
            if src is None:
                continue
            for node in ast.walk(src.tree):
                if not isinstance(node, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                    continue
                if node.name != fn_name:
                    continue
                for sub in ast.walk(node):
                    if (isinstance(sub, ast.Constant)
                            and isinstance(sub.value, str)
                            and sub.value.startswith(
                                EXPOSITION_PREFIX)):
                        fams.append((sub.value, rel, sub.lineno))
        return fams

    def _check_exposition(self, files: Sequence[core.SourceFile],
                          ctx: core.RunContext
                          ) -> Iterable[core.Finding]:
        relevant = {rel for rel, _ in EXPOSITION_FUNCS}
        if not relevant & {s.rel for s in files}:
            return   # partial scan without the exposition module
        doc = _doc_section_names(ctx.docs_root, 'observability.md',
                                 '## Prometheus exposition')
        if doc is None:
            yield core.Finding(
                self.code, 'docs/observability.md', 0,
                'Prometheus exposition catalog ("## Prometheus '
                'exposition") not found in docs/observability.md')
            return
        documented, where = doc
        fams = self._exposition_families(files)
        seen: Set[str] = set()
        for fam, rel, lineno in fams:
            if fam in documented or fam in seen:
                continue
            seen.add(fam)
            yield core.Finding(
                self.code, rel, lineno,
                f'exposition family {fam!r} is not in '
                f'docs/observability.md\'s Prometheus exposition '
                f'catalog — scrape configs cannot discover it')
        if ctx.full_package:
            in_code = {f for f, _, _ in fams}
            for fam in sorted(documented - in_code):
                yield core.Finding(
                    self.code, 'docs/observability.md',
                    where.get(fam, 0),
                    f'cataloged exposition family {fam!r} is no '
                    f'longer emitted — a dashboard scraping it has '
                    f'flatlined')
