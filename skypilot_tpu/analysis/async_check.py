"""SKY-ASYNC: async hygiene + the event-driven-waits discipline.

Subsumes (and replaces) the grep-based sleep lints of
``tests/unit_tests/test_retry_lint.py``, as real AST findings:

1. ``time.sleep`` inside ``async def`` — anywhere in the package.
   Blocks the event loop; never allowlisted lightly.
2. Blocking I/O inside ``async def`` — ``requests.*``, ``urllib``,
   ``socket`` connects, ``subprocess`` waits, ``open()``. The loop
   serves every in-flight stream; one blocked handler stalls all.
3. Hand-rolled retry backoff inside ``async def``: a loop whose
   ``except`` handler sleeps. Retry/backoff belongs in the shared
   ``Retrier`` (utils/retry.py) — that is what makes backoff
   jittered, deadline-bound, and trace-visible everywhere at once.
4. Bare ``time.sleep`` anywhere in the wire-facing layers
   (``client/``, ``runtime/``, ``serve/``, ``infer/``) — sync context
   included. Genuine status-poll cadences are allowlisted with a
   justification; new sites fail.
5. ANY sleep (``time`` or ``asyncio``) in the serve/infer hot paths
   (``serve/``, ``infer/``): token delivery, drain, and resume are
   event-driven end to end (``Request.wait_progress`` /
   ``_TokenWaiter`` / the ``/drain`` long-poll); a poll loop here
   re-adds its interval to every streamed token or failover.
   Background maintenance cadences (LB replica sync) are the
   allowlisted exceptions.
6. Bare ``time.time()`` / ``time.monotonic()`` in ``serve/``: the
   control plane is clock-injectable (``utils/vclock``) so the fleet
   digital twin (docs/robustness.md "Digital twin") replays a day of
   control decisions in virtual seconds — a direct wall-clock read
   anchors a decision to machine time the twin cannot control.

One finding per call site; the allowlist pins the audited count per
``path:SKY-ASYNC`` exactly like the old grep lint pinned counts per
file — and the stale-entry check ratchets removed sites out.
"""
from __future__ import annotations

import ast
from typing import Iterable, Sequence

from skypilot_tpu.analysis import core
from skypilot_tpu.analysis import walker

# Wire-facing layers where a bare time.sleep needs an audited
# justification even in sync context (the old test_retry_lint scope).
TIME_SLEEP_DIRS = ('client/', 'runtime/', 'serve/', 'infer/')
# Hot paths where asyncio.sleep is ALSO pinned (event-driven waits).
ANY_SLEEP_DIRS = ('serve/', 'infer/')
# Clock-seam discipline (docs/robustness.md "Digital twin"): the serve
# control plane reads time ONLY through utils/vclock (or an injected
# Clock), so the fleet digital twin can replay every control decision
# in virtual time. A bare wall-clock read here silently anchors a
# decision to machine time the twin cannot control.
CLOCK_SEAM_DIRS = ('serve/',)
_WALL_CLOCK_CALLS = frozenset(('time.time', 'time.monotonic'))

_BLOCKING_CALLS = frozenset((
    'urllib.request.urlopen', 'socket.create_connection',
    'subprocess.run', 'subprocess.call', 'subprocess.check_call',
    'subprocess.check_output', 'os.system', 'open', 'io.open',
))
_BLOCKING_PREFIXES = ('requests.',)
_SLEEPS = frozenset(('time.sleep', 'asyncio.sleep'))


def _in_dirs(rel: str, dirs) -> bool:
    return any(rel.startswith(d) for d in dirs)


class AsyncChecker(core.Checker):
    code = 'SKY-ASYNC'
    title = ('no blocking calls in async defs; waits stay '
             'event-driven; retries go through Retrier')

    def check(self, files: Sequence[core.SourceFile],
              ctx: core.RunContext) -> Iterable[core.Finding]:
        for src in files:
            yield from self._check_file(src)

    def _check_file(self,
                    src: core.SourceFile) -> Iterable[core.Finding]:
        # One finding per line: a sleep can match several rules (e.g.
        # a retry-loop backoff is also a sleep site) but it is one
        # violation for the allowlist count. The retry-loop rule wins
        # (most specific).
        found = {}
        for node in ast.walk(src.tree):
            if isinstance(node, (ast.While, ast.For)):
                for f in self._check_retry_loop(src, node):
                    found[f.line] = f
        for node in ast.walk(src.tree):
            if isinstance(node, ast.Call):
                f = self._check_call(src, node)
                if f is not None and f.line not in found:
                    found[f.line] = f
        yield from (found[line] for line in sorted(found))

    def _check_call(self, src: core.SourceFile, node: ast.Call):
        name = walker.call_name(node)
        if name is None:
            return None
        in_async = walker.in_async_function(node)
        if name == 'time.sleep':
            if in_async:
                return core.Finding(
                    self.code, src.rel, node.lineno,
                    'time.sleep inside async def blocks the event '
                    'loop (await asyncio.sleep, or an event/condition '
                    'wait off-loop)')
            if _in_dirs(src.rel, TIME_SLEEP_DIRS):
                return core.Finding(
                    self.code, src.rel, node.lineno,
                    'bare time.sleep in a wire-facing layer — '
                    'retries go through utils/retry.Retrier; a '
                    'genuine status-poll cadence needs an audited '
                    'allowlist entry')
        elif (name in _WALL_CLOCK_CALLS
                and _in_dirs(src.rel, CLOCK_SEAM_DIRS)):
            return core.Finding(
                self.code, src.rel, node.lineno,
                f'bare {name}() in the serve control plane — read '
                f'through the utils/vclock clock seam (vclock.now()/'
                f'.monotonic() or an injected Clock) so the fleet '
                f'digital twin can replay this decision in virtual '
                f'time (docs/robustness.md "Digital twin")')
        elif name == 'asyncio.sleep':
            if _in_dirs(src.rel, ANY_SLEEP_DIRS):
                return core.Finding(
                    self.code, src.rel, node.lineno,
                    'asyncio.sleep in the serve/infer hot path — '
                    'token delivery, drain and resume are '
                    'event-driven (Event/Condition waits); a poll '
                    'loop re-adds its interval to every token or '
                    'failover')
        elif in_async and (name in _BLOCKING_CALLS
                           or name.startswith(_BLOCKING_PREFIXES)):
            return core.Finding(
                self.code, src.rel, node.lineno,
                f'blocking call {name}() inside async def — stalls '
                f'every in-flight stream on this loop (use '
                f'asyncio.to_thread or the aiohttp session)')
        return None

    def _check_retry_loop(self, src: core.SourceFile,
                          loop: ast.AST) -> Iterable[core.Finding]:
        if not walker.in_async_function(loop):
            return
        for sub in walker.walk_function_body(loop):
            if not isinstance(sub, ast.ExceptHandler):
                continue
            for call in ast.walk(sub):
                if (isinstance(call, ast.Call)
                        and walker.call_name(call) in _SLEEPS):
                    yield core.Finding(
                        self.code, src.rel, call.lineno,
                        'sleep inside an except handler inside a '
                        'loop in async def — a hand-rolled retry '
                        'backoff; route it through '
                        'utils/retry.Retrier (jitter, deadlines, '
                        'retry.<name> trace spans)')
                    break
