"""Core of the `sky-tpu lint` static-analysis framework.

The serving stack's correctness rests on conventions that ordinary
tests cannot see: engine state is only touched under ``_lock``, waits
are event-driven, failpoint/metric names stay in sync with the docs
catalogs, and nothing in a jitted path branches on traced values. This
module is the plumbing every checker shares:

- :class:`Finding` — one violation, keyed ``path:code`` for the
  allowlist;
- :class:`SourceFile` — parsed module (text + AST + parent links);
- :class:`Checker` — the plugin protocol (``check(files, ctx)``);
- :class:`Report` — findings grouped against the audited allowlist,
  with the same two-sided discipline as the old grep lints: counts
  above an allowlist entry fail (new violation), counts below fail too
  (stale entry silently granting headroom — ratchet it down).

Allowlist semantics: entries are ``'<path>:<CODE>': (max_count,
justification)`` with paths package-relative (posix). Counting per
``path:code`` (not per line) keeps entries stable across unrelated
edits to the same file while still refusing any *new* site.
"""
from __future__ import annotations

import ast
import collections
import dataclasses
import json
import os
from typing import Dict, Iterable, List, Optional, Sequence, Tuple


@dataclasses.dataclass(frozen=True)
class Finding:
    code: str       # checker code, e.g. 'SKY-LOCK'
    path: str       # package-relative posix path ('infer/engine.py')
    line: int
    message: str
    # 'error' findings beyond the allowlist fail the gate; 'warn'
    # findings are reported (and counted by the ratchet) but do not
    # flip Report.ok — the SKY-HOLD severity tiers.
    severity: str = 'error'
    # Interprocedural findings carry the call chain that produced
    # them (outermost caller first), e.g.
    # ('h_metrics', 'EnginePool.metrics', '_merge_tenants').
    chain: Optional[Tuple[str, ...]] = None

    @property
    def key(self) -> str:
        return f'{self.path}:{self.code}'

    def to_dict(self) -> Dict[str, object]:
        out: Dict[str, object] = {
            'code': self.code, 'path': self.path,
            'line': self.line, 'message': self.message,
            'severity': self.severity}
        if self.chain:
            out['chain'] = list(self.chain)
        return out


class SourceFile:
    """One parsed module: text, lines, AST with parent links."""

    def __init__(self, abs_path: str, rel: str) -> None:
        self.abs_path = abs_path
        self.rel = rel
        with open(abs_path, encoding='utf-8') as f:
            self.text = f.read()
        self.lines = self.text.splitlines()
        self.parse_error: Optional[SyntaxError] = None
        try:
            self.tree: Optional[ast.Module] = ast.parse(
                self.text, filename=rel)
        except SyntaxError as e:
            self.tree = None
            self.parse_error = e
            return
        for node in ast.walk(self.tree):
            for child in ast.iter_child_nodes(node):
                child._sky_parent = node    # type: ignore[attr-defined]

    def line(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ''


@dataclasses.dataclass
class RunContext:
    pkg_root: str               # package root rel paths are relative to
    docs_root: Optional[str]    # docs/ directory (registry checker)
    full_package: bool          # scanned the whole package (enables
    # the doc→code direction of SKY-REGISTRY, which would false-fire
    # on a partial scan)


class Checker:
    """Plugin protocol. Subclasses set ``code``/``title`` and yield
    findings from ``check``."""

    code: str = ''
    title: str = ''

    def check(self, files: Sequence[SourceFile],
              ctx: RunContext) -> Iterable[Finding]:
        raise NotImplementedError


Allowlist = Dict[str, Tuple[int, str]]


@dataclasses.dataclass
class Report:
    findings: List[Finding]
    allowlist: Allowlist
    checker_codes: List[str]
    # Rel paths actually scanned + whether this was the whole package
    # — staleness is only judged for entries the scan could have seen
    # (a partial `sky-tpu lint subdir` must not call every other
    # file's pins stale).
    scanned: frozenset = frozenset()
    full_package: bool = True

    @property
    def counts(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for f in self.findings:
            out[f.key] = out.get(f.key, 0) + 1
        return out

    @property
    def offenders(self) -> Dict[str, List[Finding]]:
        """Findings beyond the allowlisted count, grouped by key."""
        out: Dict[str, List[Finding]] = {}
        for key, n in self.counts.items():
            cap = self.allowlist.get(key, (0, ''))[0]
            if n > cap:
                out[key] = [f for f in self.findings if f.key == key]
        return out

    @property
    def hard_offenders(self) -> Dict[str, List[Finding]]:
        """Offender keys with at least one error-severity finding —
        the set that fails the gate. Warn-tier-only offender keys
        (SKY-HOLD's lower tiers) are surfaced but advisory."""
        return {k: v for k, v in self.offenders.items()
                if any(f.severity == 'error' for f in v)}

    @property
    def stale(self) -> Dict[str, Tuple[int, int]]:
        """Allowlist entries whose sites were since removed (cap >
        actual) — they must be ratcheted down, or they silently grant
        headroom for new violations. Only entries whose checker ran
        are judged (a single-checker run must not call every other
        checker's pins stale)."""
        counts = self.counts
        out: Dict[str, Tuple[int, int]] = {}
        for key, (cap, _why) in self.allowlist.items():
            path, code = key.rsplit(':', 1)
            if code not in self.checker_codes:
                continue
            if path not in self.scanned and not (
                    self.full_package and path.startswith('docs/')):
                continue
            if counts.get(key, 0) < cap:
                out[key] = (cap, counts.get(key, 0))
        return out

    @property
    def ok(self) -> bool:
        return not self.hard_offenders and not self.stale

    def to_json(self) -> str:
        hard = self.hard_offenders
        return json.dumps({
            'ok': self.ok,
            'findings': [f.to_dict() for f in self.findings],
            'offenders': {k: [f.to_dict() for f in v]
                          for k, v in self.offenders.items()},
            'warn_offenders': sorted(
                k for k in self.offenders if k not in hard),
            'stale_allowlist': {k: {'allowed': cap, 'found': n}
                                for k, (cap, n) in self.stale.items()},
        }, indent=2, sort_keys=True)

    def render_text(self, verbose: bool = False) -> str:
        lines: List[str] = []
        offenders = self.offenders
        hard = self.hard_offenders
        if verbose and self.findings:
            lines.append('All findings (including allowlisted):')
            for f in sorted(self.findings,
                            key=lambda f: (f.path, f.line)):
                lines.append(f'  {f.path}:{f.line} [{f.code}] '
                             f'{f.message}')
            lines.append('')
        for key in sorted(offenders):
            cap, why = self.allowlist.get(key, (0, ''))
            head = f'{key}: {len(offenders[key])} finding(s)'
            if key not in hard:
                head += ' [warn tier — advisory, does not fail]'
            if cap:
                head += f' (allowlist covers {cap}: {why})'
            lines.append(head)
            for f in offenders[key]:
                lines.append(f'  {f.path}:{f.line} {f.message}')
                if f.chain:
                    lines.append(
                        f'    call chain: {" -> ".join(f.chain)}')
        for key, (cap, n) in sorted(self.stale.items()):
            lines.append(
                f'{key}: allowlist grants {cap} but only {n} found — '
                f'ratchet the entry down (stale caps hide new sites)')
        n_off = sum(len(v) for v in hard.values())
        if self.ok:
            lines.append(
                f'lint clean: {len(self.findings)} finding(s), all '
                f'within the audited allowlist.')
        else:
            lines.append(
                f'lint FAILED: {n_off} finding(s) beyond the '
                f'allowlist, {len(self.stale)} stale allowlist '
                f'entr(y/ies).')
        return '\n'.join(lines)


# Parsed-module cache: (mtime_ns, size, SourceFile) by absolute path,
# LRU-bounded (a long-lived process linting many trees — the test
# suite's per-test fixture packages — must not accumulate dead ASTs
# forever). Parsing + parent-linking dominates lint wall-clock;
# repeated runs in one process (the tier-1 gate + canaries,
# `--changed` after a full run) reuse the tree. Identity stability
# doubles as the content signature lockflow's memo keys on.
_SOURCE_CACHE: ('collections.OrderedDict'
                '[str, Tuple[int, int, SourceFile]]') = (
    collections.OrderedDict())
_SOURCE_CACHE_LIMIT = 2048


def _load_source(abs_path: str, rel: str) -> 'SourceFile':
    try:
        st = os.stat(abs_path)
        sig = (st.st_mtime_ns, st.st_size)
    except OSError:
        return SourceFile(abs_path, rel)
    hit = _SOURCE_CACHE.get(abs_path)
    if hit is not None and hit[:2] == sig and hit[2].rel == rel:
        _SOURCE_CACHE.move_to_end(abs_path)
        return hit[2]
    src = SourceFile(abs_path, rel)
    _SOURCE_CACHE[abs_path] = (sig[0], sig[1], src)
    _SOURCE_CACHE.move_to_end(abs_path)
    while len(_SOURCE_CACHE) > _SOURCE_CACHE_LIMIT:
        _SOURCE_CACHE.popitem(last=False)
    return src


def clear_source_cache() -> None:
    _SOURCE_CACHE.clear()


def load_files(root: str, pkg_root: str) -> List[SourceFile]:
    """Every .py under ``root``; rel paths computed against
    ``pkg_root`` so allowlist keys are stable for partial scans."""
    files: List[SourceFile] = []
    if os.path.isfile(root):
        rel = os.path.relpath(root, pkg_root).replace(os.sep, '/')
        return [_load_source(root, rel)]
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(d for d in dirnames
                             if d != '__pycache__'
                             and not d.startswith('.'))
        for fname in sorted(filenames):
            if not fname.endswith('.py'):
                continue
            abs_path = os.path.join(dirpath, fname)
            rel = os.path.relpath(abs_path, pkg_root).replace(
                os.sep, '/')
            files.append(_load_source(abs_path, rel))
    return files


def run_checkers(checkers: Sequence[Checker],
                 root: Optional[str] = None,
                 pkg_root: Optional[str] = None,
                 docs_root: Optional[str] = None,
                 allowlist: Optional[Allowlist] = None,
                 report_paths: Optional[frozenset] = None) -> Report:
    """Run ``checkers`` over ``root`` (default: the installed
    skypilot_tpu package) and judge findings against ``allowlist``.

    ``report_paths`` is the incremental (`sky-tpu lint --changed`)
    contract: the WHOLE tree is still scanned — the interprocedural
    passes need the full call graph to be sound — but findings are
    reported, and allowlist staleness judged, only for the given
    package-relative paths."""
    if pkg_root is None:
        import skypilot_tpu
        pkg_root = os.path.dirname(os.path.abspath(
            skypilot_tpu.__file__))
    if root is None:
        root = pkg_root
    root = os.path.abspath(root)
    pkg_root = os.path.abspath(pkg_root)
    if not os.path.exists(root):
        # A typo'd path must never read as a clean gate ('lint clean:
        # 0 findings' with zero files scanned is a green light with
        # no coverage).
        raise FileNotFoundError(f'lint root does not exist: {root}')
    if docs_root is None:
        candidate = os.path.join(os.path.dirname(pkg_root), 'docs')
        docs_root = candidate if os.path.isdir(candidate) else None
    ctx = RunContext(pkg_root=pkg_root, docs_root=docs_root,
                     full_package=(root == pkg_root))
    files = load_files(root, pkg_root)
    findings: List[Finding] = []
    for src in files:
        if src.parse_error is not None:
            findings.append(Finding(
                'SKY-PARSE', src.rel,
                src.parse_error.lineno or 0,
                f'file does not parse: {src.parse_error.msg}'))
    parsed = [s for s in files if s.tree is not None]
    for checker in checkers:
        findings.extend(checker.check(parsed, ctx))
    scanned = frozenset(s.rel for s in files)
    if report_paths is not None:
        # Interprocedural (chain-carrying) findings always survive the
        # filter: a changed callee can introduce a violation whose
        # report site is an UNCHANGED caller (annotation verification
        # fires at the call site) — dropping it would make the
        # pre-commit `--changed` gate print clean while full-package
        # CI fails on the same tree.
        findings = [f for f in findings
                    if f.path in report_paths or f.chain]
        scanned = frozenset(report_paths) & (
            scanned | frozenset(p for p in report_paths
                                if p.startswith('docs/')))
    return Report(findings=findings,
                  allowlist=dict(allowlist or {}),
                  checker_codes=[c.code for c in checkers],
                  scanned=scanned,
                  full_package=ctx.full_package)
