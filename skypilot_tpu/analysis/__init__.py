"""`sky-tpu lint`: AST-based invariant checkers for the serving
stack's unwritten rules.

PRs 2–5 built correctness on conventions nothing enforced — engine
state only under ``_lock``, event-driven waits, failpoint/metric names
in sync with the docs catalogs, zero new compiled programs in jitted
paths. This package is the static gate that holds those invariants
through refactors (docs/static-analysis.md has the full catalog):

=============  =======================================================
SKY-LOCK       fields in a class's ``_GUARDED_BY`` registry accessed
               only under their lock / declared context — now
               INTERPROCEDURAL: helpers are legal when the lock is
               held at every resolved call site, and every
               ``# holds:`` annotation is verified against its real
               callers (lockflow.py)
SKY-ORDER      the global lock-acquisition-order graph is acyclic;
               non-reentrant locks are never re-acquired; the
               canonical ``LOCK_ORDER`` is never contradicted
SKY-HOLD       no blocking operation (await / sleep / net /
               subprocess / device readback / file IO) while a lock
               is held, with severity tiers
SKY-ASYNC      no blocking calls in ``async def``; waits stay
               event-driven; retries go through ``Retrier``
SKY-EXCEPT     async serve/infer code never swallows connection-reset
               / cancellation signals under broad excepts
SKY-TRACE      no concretization or data-dependent branching in
               jit-reachable code (the recompile hazard)
SKY-REGISTRY   failpoint sites and serving-metric keys match the docs
               catalogs, both directions
=============  =======================================================

Usage::

    sky-tpu lint                       # whole package, human output
    sky-tpu lint --json                # machine-readable
    sky-tpu lint skypilot_tpu/infer    # one subtree
    sky-tpu lint --changed             # only files changed vs git

Exit status is non-zero when any finding exceeds the audited
allowlist (``analysis/allowlist.py`` — entries are
``'<path>:<CODE>': (count, justification)``) or an allowlist entry
went stale. The tier-1 test ``tests/unit_tests/test_analysis.py``
runs the same gate in CI.
"""
from __future__ import annotations

from typing import List, Optional, Sequence

from skypilot_tpu.analysis import core
from skypilot_tpu.analysis.allowlist import ALLOWLIST, LOCK_ORDER
from skypilot_tpu.analysis.async_check import AsyncChecker
from skypilot_tpu.analysis.core import (Checker, Finding, Report,
                                        RunContext, SourceFile)
from skypilot_tpu.analysis.except_check import ExceptChecker
from skypilot_tpu.analysis.hold_check import HoldChecker
from skypilot_tpu.analysis.lock_check import LockChecker
from skypilot_tpu.analysis.order_check import OrderChecker
from skypilot_tpu.analysis.registry_check import RegistryChecker
from skypilot_tpu.analysis.trace_check import TraceChecker


def all_checkers() -> List[core.Checker]:
    """A fresh instance of every registered checker."""
    return [LockChecker(), OrderChecker(), HoldChecker(),
            AsyncChecker(), ExceptChecker(), TraceChecker(),
            RegistryChecker()]


def run(root: Optional[str] = None,
        pkg_root: Optional[str] = None,
        docs_root: Optional[str] = None,
        checkers: Optional[Sequence[core.Checker]] = None,
        allowlist: Optional[core.Allowlist] = None,
        report_paths: Optional[frozenset] = None) -> Report:
    """Run the suite. Defaults: all checkers over the installed
    package against the shipped allowlist. ``report_paths`` scopes
    the REPORT (not the scan — interprocedural passes always see the
    whole tree) to the given package-relative paths; the CLI's
    ``--changed`` feeds it the git diff."""
    return core.run_checkers(
        checkers if checkers is not None else all_checkers(),
        root=root, pkg_root=pkg_root, docs_root=docs_root,
        allowlist=ALLOWLIST if allowlist is None else allowlist,
        report_paths=report_paths)


__all__ = ['run', 'all_checkers', 'ALLOWLIST', 'LOCK_ORDER',
           'Checker', 'Finding', 'Report', 'RunContext',
           'SourceFile', 'LockChecker', 'OrderChecker',
           'HoldChecker', 'AsyncChecker', 'ExceptChecker',
           'TraceChecker', 'RegistryChecker']
