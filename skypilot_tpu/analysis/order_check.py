"""SKY-ORDER: global lock-acquisition-order discipline.

Deadlock by lock-order inversion is the highest-severity latent bug
class in a system whose step thread, HTTP handler threads, LB event
loop and lockstep drivers all share locks: thread 1 acquires A then
B, thread 2 acquires B then A, and both park forever. The inversion
is invisible to lexical checks because the two acquisitions usually
live in different functions — PR 7/8 added exactly such lock-crossing
call chains (engine -> scheduler -> policy dispatch).

On top of the lock-flow dataflow (lockflow.py) this checker builds
the global acquisition-order graph: an edge ``A -> B`` whenever B is
acquired while A may be held — lexically nested ``with`` blocks, or
transitively (a call made under A reaches a function that acquires
B). Findings:

1. **Cycles** in the graph (potential deadlock): reported once per
   cycle, at the lexicographically-first contributing acquisition
   site, with the full edge list and an example call chain per edge.
2. **Re-entrant acquisition of a non-reentrant lock**: acquiring L
   while L may already be held, when L is a plain ``threading.Lock``
   (or ``multiprocessing.Lock``). ``RLock``/``Condition`` (which
   wraps an RLock) are exempt; locks whose kind cannot be determined
   statically are skipped rather than guessed.
3. **Canonical-order violations**: ``analysis/allowlist.py`` may
   declare ``LOCK_ORDER``, the audited global acquisition order. Any
   edge contradicting it fails even before a full cycle closes — the
   ratchet that keeps a second inversion from ever landing.

The pseudo-lock ``event-loop`` (asyncio confinement) never
participates: it is not a mutex.
"""
from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from skypilot_tpu.analysis import core
from skypilot_tpu.analysis import lockflow


class _OrderEdge:
    __slots__ = ('src', 'dst', 'path', 'line', 'chain')

    def __init__(self, src: str, dst: str, path: str, line: int,
                 chain: List[str]) -> None:
        self.src = src
        self.dst = dst
        self.path = path
        self.line = line
        self.chain = chain


class OrderChecker(core.Checker):
    code = 'SKY-ORDER'
    title = ('lock acquisition order is globally acyclic and '
             'non-reentrant locks are never re-acquired')

    def __init__(self,
                 lock_order: Optional[Sequence[str]] = None) -> None:
        if lock_order is None:
            from skypilot_tpu.analysis import allowlist
            lock_order = getattr(allowlist, 'LOCK_ORDER', ())
        self.lock_order = list(lock_order)

    def check(self, files: Sequence[core.SourceFile],
              ctx: core.RunContext) -> Iterable[core.Finding]:
        flow = lockflow.analyze(files)
        edges: Dict[Tuple[str, str], _OrderEdge] = {}
        for key, summ in flow.summaries.items():
            info = flow.funcs[key]
            entry = flow._entry_locks(key)
            for acq in summ.acquires:
                if flow.kind(acq.lock) == 'asyncio':
                    # asyncio primitives are loop-confined; mixing
                    # them into the THREAD deadlock graph only adds
                    # noise (they cannot park an OS thread).
                    continue
                prior = set(acq.held_before) | entry
                prior.discard(lockflow.EVENT_LOOP)
                prior = {p for p in prior
                         if flow.kind(p) != 'asyncio'}
                yield from self._check_reentry(flow, info, acq, prior)
                for p in sorted(prior):
                    if p == acq.lock:
                        continue
                    edge_key = (p, acq.lock)
                    if edge_key in edges:
                        continue
                    chain = (flow.holding_chain(key, p)
                             if p not in acq.held_before
                             else [info.qualname])
                    edges[edge_key] = _OrderEdge(
                        p, acq.lock, info.src.rel, acq.line, chain)
        yield from self._check_canonical(edges)
        yield from self._check_cycles(edges)

    # -- re-entrancy -------------------------------------------------------
    def _check_reentry(self, flow: 'lockflow.LockFlow', info, acq,
                       prior) -> Iterable[core.Finding]:
        already = [p for p in prior
                   if p == acq.lock
                   or (lockflow.base(p) == lockflow.base(acq.lock)
                       and ('.' not in p or '.' not in acq.lock))]
        if not already:
            return
        kind = flow.kind(acq.lock)
        if kind in (None, 'RLock', 'Condition', 'asyncio'):
            return
        held_via = already[0]
        chain = (flow.holding_chain(info.key, held_via)
                 if held_via not in acq.held_before
                 else [info.qualname])
        yield core.Finding(
            self.code, info.src.rel, acq.line,
            f're-entrant acquisition of non-reentrant lock '
            f'{acq.lock} (threading.Lock) in {info.qualname} — the '
            f'second acquire self-deadlocks; use RLock or hoist the '
            f'inner acquisition out of the held region',
            chain=tuple(chain))

    # -- canonical order ---------------------------------------------------
    def _order_index(self, lock: str) -> Optional[int]:
        for i, entry in enumerate(self.lock_order):
            if entry == lock or (
                    lockflow.base(entry) == lockflow.base(lock)
                    and ('.' not in entry or '.' not in lock)):
                return i
        return None

    def _check_canonical(self, edges: Dict[Tuple[str, str],
                                           _OrderEdge]
                         ) -> Iterable[core.Finding]:
        for (src, dst), e in sorted(edges.items()):
            i, j = self._order_index(src), self._order_index(dst)
            if i is None or j is None or i <= j:
                continue
            yield core.Finding(
                self.code, e.path, e.line,
                f'acquisition order {src} -> {dst} contradicts the '
                f'canonical LOCK_ORDER (analysis/allowlist.py ranks '
                f'{dst} before {src}) — a thread honoring the '
                f'canonical order can deadlock against this path',
                chain=tuple(e.chain))

    # -- cycles ------------------------------------------------------------
    def _check_cycles(self, edges: Dict[Tuple[str, str], _OrderEdge]
                      ) -> Iterable[core.Finding]:
        graph: Dict[str, List[str]] = {}
        for (src, dst) in edges:
            graph.setdefault(src, []).append(dst)
            graph.setdefault(dst, [])
        for scc in _sccs(graph):
            if len(scc) < 2:
                continue
            members = sorted(scc)
            cyc_edges = sorted(
                (e for (s, d), e in edges.items()
                 if s in scc and d in scc),
                key=lambda e: (e.path, e.line))
            site = cyc_edges[0]
            detail = '; '.join(
                f'{e.src} -> {e.dst} at {e.path}:{e.line} '
                f'(via {" -> ".join(e.chain)})'
                for e in cyc_edges[:4])
            yield core.Finding(
                self.code, site.path, site.line,
                f'lock-order cycle {{{", ".join(members)}}} — '
                f'potential deadlock: {detail}. Pick one global '
                f'order, refactor the inverted path, and document it '
                f'in LOCK_ORDER (analysis/allowlist.py)',
                chain=tuple(site.chain))


def _sccs(graph: Dict[str, List[str]]) -> List[List[str]]:
    """Tarjan's strongly-connected components, iterative."""
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on_stack: Dict[str, bool] = {}
    stack: List[str] = []
    out: List[List[str]] = []
    counter = [0]

    for root in sorted(graph):
        if root in index:
            continue
        work: List[Tuple[str, int]] = [(root, 0)]
        while work:
            node, pi = work[-1]
            if pi == 0:
                index[node] = low[node] = counter[0]
                counter[0] += 1
                stack.append(node)
                on_stack[node] = True
            advanced = False
            succs = graph.get(node, [])
            while pi < len(succs):
                succ = succs[pi]
                pi += 1
                if succ not in index:
                    work[-1] = (node, pi)
                    work.append((succ, 0))
                    advanced = True
                    break
                if on_stack.get(succ):
                    low[node] = min(low[node], index[succ])
            if advanced:
                continue
            work.pop()
            if low[node] == index[node]:
                comp: List[str] = []
                while True:
                    top = stack.pop()
                    on_stack[top] = False
                    comp.append(top)
                    if top == node:
                        break
                out.append(comp)
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
    return out
