"""SKY-EXCEPT: no broad exception swallowing in serve/infer network
paths.

The PR 5 bug class this checker exists for: the serve LB's
upstream-error handler caught a broad exception family and thereby
swallowed aiohttp's ``ClientConnectionResetError`` raised on writes to
a *gone client* — mis-counting client aborts as replica failures and
feeding the circuit breaker. The general shape: in async network code,
a broad ``except`` absorbs connection-reset / cancellation signals
that deserved their own classification, and the error accounting (or
the cancellation itself) silently corrupts.

Rule: inside ``async def`` bodies in ``serve/`` and ``infer/``, a
broad handler — bare ``except:``, ``except Exception``,
``except BaseException`` — or a broad ``contextlib.suppress(Exception
| BaseException)`` is a finding UNLESS:

- the handler re-raises (a ``raise`` statement anywhere in its body:
  classification happened, the broad arm is a cleanup backstop), or
- an EARLIER handler of the same ``try`` names a connection/
  cancellation type (``asyncio.CancelledError``, ``ConnectionError``
  family, ``OSError``, aiohttp client errors, or one of the LB's
  classification exceptions) — the dangerous signals were explicitly
  classified before the broad arm.

Bare ``except:`` and ``except BaseException`` additionally swallow
``asyncio.CancelledError`` (which ``except Exception`` does not — it
is a ``BaseException`` since 3.8), so their message says so.

Sync code and other packages are out of scope: the broad handlers
there guard DB writes, JSON parses, and teardown paths where
fail-open is the documented contract. Surviving in-scope sites carry
a one-line justification in the allowlist.
"""
from __future__ import annotations

import ast
from typing import Iterable, Optional, Sequence

from skypilot_tpu.analysis import core
from skypilot_tpu.analysis import walker

SCOPE_DIRS = ('serve/', 'infer/')

_BROAD = frozenset(('Exception', 'BaseException'))
# Types whose presence in an earlier handler counts as explicit
# classification of the reset/cancellation family.
_CLASSIFYING = frozenset((
    'CancelledError', 'ConnectionError', 'ConnectionResetError',
    'BrokenPipeError', 'OSError', 'TimeoutError', 'ClientError',
    'ClientConnectionError', 'ClientConnectionResetError',
    '_ClientGone', '_UpstreamDead', '_PreStreamFailure',
    '_ReplicaSaturated'))


def _type_names(expr: Optional[ast.AST]):
    """Leaf type names of an except clause's type expression."""
    if expr is None:
        return []
    if isinstance(expr, ast.Tuple):
        out = []
        for e in expr.elts:
            out.extend(_type_names(e))
        return out
    name = walker.dotted_name(expr)
    if name is None:
        return []
    return [name.rsplit('.', 1)[-1]]


def _reraises(handler: ast.ExceptHandler) -> bool:
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return True
    return False


class ExceptChecker(core.Checker):
    code = 'SKY-EXCEPT'
    title = ('async serve/infer code must not swallow reset/'
             'cancellation signals under broad excepts')

    def check(self, files: Sequence[core.SourceFile],
              ctx: core.RunContext) -> Iterable[core.Finding]:
        for src in files:
            if not any(src.rel.startswith(d) for d in SCOPE_DIRS):
                continue
            yield from self._check_file(src)

    def _check_file(self,
                    src: core.SourceFile) -> Iterable[core.Finding]:
        for node in ast.walk(src.tree):
            if isinstance(node, ast.Try):
                if not walker.in_async_function(node):
                    continue
                yield from self._check_try(src, node)
            elif isinstance(node, ast.Call):
                if not walker.in_async_function(node):
                    continue
                f = self._check_suppress(src, node)
                if f is not None:
                    yield f

    def _check_try(self, src: core.SourceFile,
                   node: ast.Try) -> Iterable[core.Finding]:
        classified = False
        for handler in node.handlers:
            names = _type_names(handler.type)
            broad = (handler.type is None
                     or any(n in _BROAD for n in names))
            if not broad:
                if any(n in _CLASSIFYING for n in names):
                    classified = True
                continue
            if _reraises(handler) or classified:
                continue
            swallows = ('connection resets AND asyncio.CancelledError'
                        if (handler.type is None
                            or 'BaseException' in names)
                        else 'connection-reset exceptions')
            label = ('bare except'
                     if handler.type is None else
                     f'except {"/".join(names)}')
            yield core.Finding(
                self.code, src.rel, handler.lineno,
                f'{label} in an async network path swallows '
                f'{swallows} without re-raising or classifying them '
                f'first (the PR-5 client-abort-counted-as-replica-'
                f'death bug class) — add narrower handlers before '
                f'it, re-raise, or allowlist with a justification')

    def _check_suppress(self, src: core.SourceFile,
                        node: ast.Call) -> Optional[core.Finding]:
        name = walker.call_name(node)
        if name is None or name.rsplit('.', 1)[-1] != 'suppress':
            return None
        broad = [a for a in node.args
                 if walker.dotted_name(a) in _BROAD]
        if not broad:
            return None
        return core.Finding(
            self.code, src.rel, node.lineno,
            f'contextlib.suppress({walker.dotted_name(broad[0])}) in '
            f'an async network path discards connection-reset '
            f'signals silently — suppress the specific expected '
            f'types, or allowlist with a justification')
