"""Interprocedural lock-set dataflow over the whole package.

PR 6's checkers were *lexical*: SKY-LOCK only saw a guarded field
mutated outside ``with self._lock`` in the same function, so a helper
called from under the lock — or a second lock acquired in a different
order three frames down — was invisible. This module computes, for
every function in the scanned set, the set of locks possibly (MAY)
and provably (MUST) held at its entry, by propagating lexical
``with <lock>:`` blocks and ``# holds:`` annotations through the call
graph. Three checkers consume it:

- **SKY-ORDER** (order_check.py): the global lock-acquisition-order
  graph — cycles (potential deadlock) and re-entrant acquisition of a
  non-reentrant lock;
- **SKY-HOLD** (hold_check.py): blocking operations while a lock is
  held, with severity tiers;
- **SKY-LOCK v2** (lock_check.py): guarded-field accesses are legal
  when the lock is held at *all* call sites reaching the accessor —
  and every ``# holds:`` annotation is verified against its real
  callers instead of being trusted.

Lock identity
-------------
A lock is identified by a qualified id ``Class.attr`` when the
acquisition is ``with self.attr:`` inside a class (or the attr was
assigned ``threading.Lock()`` in that class), ``module.attr`` for
module-level locks, or the bare attribute name when the receiver
class cannot be determined. ``# holds:`` annotations and
``_GUARDED_BY`` specs use bare names; matching is by base name
(``InferenceEngine._lock`` satisfies ``# holds: _lock``) — the same
over-approximation the lexical checker used, now applied
transitively. The pseudo-lock ``event-loop`` models asyncio
confinement: every ``async def`` holds it at entry by construction.

Call-graph resolution
---------------------
Bare names resolve through the module scope chain (SKY-TRACE's rule);
``self.meth()`` resolves through the enclosing class and its bases;
``alias.func()`` through this package's imports; ``super().meth()``
through the base-class chain; and ``obj.meth()`` falls back to *duck
dispatch* — every class method of that name across the scanned
package, provided the name is specific enough (≤ ``_DUCK_LIMIT``
defining classes, not a builtin-collection verb). Duck dispatch is
what connects the engine's ``self._sched.pop_next()`` to every
scheduler policy — the lock-crossing chains PR 7/8 added.

The analysis is memoized per (rel, SourceFile-identity) set, so the
three consuming checkers and repeated `sky-tpu lint` calls in one
process (tests, ``--changed``) pay for it once.
"""
from __future__ import annotations

import ast
import collections
from typing import (Dict, FrozenSet, Iterable, List, Optional,
                    Sequence, Set, Tuple)

from skypilot_tpu.analysis import core
from skypilot_tpu.analysis import walker

FuncKey = walker.FuncKey

EVENT_LOOP = 'event-loop'

# Lock factory calls -> kind. Condition() wraps an RLock by default,
# so re-entry through it is safe; asyncio locks are loop-confined and
# excluded from blocking/ordering analysis entirely.
_LOCK_FACTORIES = {
    'threading.Lock': 'Lock',
    'threading.RLock': 'RLock',
    'threading.Condition': 'Condition',
    'multiprocessing.Lock': 'Lock',
    'asyncio.Lock': 'asyncio',
    'asyncio.Condition': 'asyncio',
    'asyncio.Semaphore': 'asyncio',
}

# Method names too generic to duck-dispatch on: collection/threading/
# IO verbs that would wire unrelated classes together.
_DUCK_DENY = frozenset(walker.MUTATOR_METHODS) | frozenset((
    'get', 'put', 'set', 'items', 'keys', 'values', 'copy', 'join',
    'split', 'strip', 'read', 'write', 'readline', 'readlines',
    'flush', 'close', 'open', 'send', 'recv', 'encode', 'decode',
    'format', 'count', 'index', 'startswith', 'endswith', 'lower',
    'upper', 'replace', 'wait', 'notify', 'notify_all', 'acquire',
    'release', 'start', 'is_set', 'result', 'done', 'info', 'debug',
    'warning', 'error', 'exception', 'critical', 'log', 'get_event_loop',
))

# A method name defined in more than this many classes is treated as
# too generic to dispatch on (the edges would be mostly noise).
_DUCK_LIMIT = 8

# Consumers of a bare method REFERENCE (`key=self._normalized_load`)
# that invoke it synchronously, on the referencing thread, while the
# reference site's locks are still held — only these let the held set
# flow into the callee's entry sets. Everything else (executor.submit,
# threading.Timer, storing the reference for later) runs the callback
# AFTER the with-block exits, usually on another thread: claiming the
# lock is held there would let SKY-LOCK v2 bless a real data race.
_SYNC_REF_CONSUMERS = frozenset((
    'min', 'max', 'sorted', 'map', 'filter', 'next', 'any', 'all',
    'sum', 'list', 'tuple', 'set', 'functools.reduce',
))
# asyncio deferrals stay ON the loop: the callback keeps event-loop
# confinement but no threading lock survives until it runs.
_LOOP_DEFER_CONSUMERS = frozenset((
    'call_soon', 'call_later', 'call_at', 'call_soon_threadsafe',
    'create_task', 'ensure_future',
))


class Acquire:
    """One lock acquisition site (with-block or manual acquire())."""

    __slots__ = ('lock', 'line', 'held_before')

    def __init__(self, lock: str, line: int,
                 held_before: Tuple[str, ...]) -> None:
        self.lock = lock
        self.line = line
        self.held_before = held_before


class CallSite:
    """One resolved call: targets + the locks lexically held at it.

    ``deferred`` marks method-reference edges whose callee runs LATER
    (executor.submit, Timer, stored callback) — ``held`` is already
    stripped for those, and the fixpoints must not let the CALLER's
    entry locks flow across either (the callback does not inherit its
    creator's lock context)."""

    __slots__ = ('targets', 'line', 'held', 'deferred')

    def __init__(self, targets: Tuple[FuncKey, ...], line: int,
                 held: FrozenSet[str],
                 deferred: bool = False) -> None:
        self.targets = targets
        self.line = line
        self.held = held
        self.deferred = deferred


class Summary:
    __slots__ = ('acquires', 'calls', 'annotations', 'is_async')

    def __init__(self) -> None:
        self.acquires: List[Acquire] = []
        self.calls: List[CallSite] = []
        self.annotations: FrozenSet[str] = frozenset()
        self.is_async = False


class Edge:
    __slots__ = ('caller', 'line', 'held', 'targets', 'deferred')

    def __init__(self, caller: FuncKey, line: int,
                 held: FrozenSet[str],
                 targets: Tuple[FuncKey, ...] = (),
                 deferred: bool = False) -> None:
        self.caller = caller
        self.line = line
        self.held = held
        # Every candidate the originating call site resolved to —
        # when dispatch was ambiguous (duck), callers can tell.
        self.targets = targets
        self.deferred = deferred


def base(lock: str) -> str:
    return lock.rsplit('.', 1)[-1]


def has_base(locks: Iterable[str], name: str) -> bool:
    """Whether any lock id in ``locks`` matches ``name`` by base name
    (bare annotations match any class-qualified id)."""
    want = base(name)
    return any(base(l) == want for l in locks)


class LockFlow:
    """The computed dataflow for one file set."""

    def __init__(self, files: Sequence[core.SourceFile]) -> None:
        self.files = list(files)
        self.by_rel = {s.rel: s for s in self.files}
        self.index = walker.index_functions(self.files)
        self.funcs: Dict[FuncKey, walker.FuncInfo] = {}
        for rel, funcs in self.index.items():
            for info in funcs.values():
                self.funcs[info.key] = info
        # lock id -> (kind, declaring module rel)
        self.universe: Dict[str, Tuple[str, str]] = {}
        # class name -> (module rel, {method -> qualname}, [base names])
        self._classes: Dict[str, List[Tuple[str, Dict[str, str],
                                            List[str]]]] = {}
        # method name -> [FuncKey, ...] (duck-dispatch index)
        self._methods: Dict[str, List[FuncKey]] = {}
        # (class name, attr) -> attr's class, from `self.attr =
        # ClassName(...)` constructor assignments — lets
        # `self.breaker.snapshot()` resolve to CircuitBreaker.snapshot
        # precisely instead of duck-matching every `.snapshot` in the
        # package.
        self._attr_types: Dict[Tuple[str, str], str] = {}
        # base class name -> direct subclasses (virtual dispatch:
        # `self._on_replica_change()` in the base must reach every
        # override, or overrides look caller-less).
        self._subs: Dict[str, Set[str]] = {}
        self._collect_universe_and_classes()
        self.summaries: Dict[FuncKey, Summary] = {}
        self._build_summaries()
        self.in_edges: Dict[FuncKey, List[Edge]] = (
            collections.defaultdict(list))
        for key, summ in self.summaries.items():
            for call in summ.calls:
                for tgt in call.targets:
                    self.in_edges[tgt].append(
                        Edge(key, call.line, call.held,
                             call.targets, call.deferred))
        # may_entry[f]: lock -> provenance. Provenance is None when
        # the lock comes from f's own `# holds:` annotation, else
        # (caller key, call line, lexical: bool) — lexical True means
        # the caller held it lexically AT the call site (chain ends
        # there), False means it flowed from the caller's own entry.
        self.may_entry: Dict[FuncKey, Dict[
            str, Optional[Tuple[FuncKey, int, bool]]]] = {}
        self._fixpoint_may()
        # must_entry[f]: locks provably held at entry on EVERY
        # resolved chain (annotation-trusted for root functions).
        self.must_entry: Dict[FuncKey, FrozenSet[str]] = {}
        self._fixpoint_must()

    # -- construction ------------------------------------------------------
    def _collect_universe_and_classes(self) -> None:
        for src in self.files:
            mod = src.rel.rsplit('/', 1)[-1][:-3]  # basename, no .py
            for node in ast.walk(src.tree):
                if isinstance(node, ast.ClassDef):
                    methods = {
                        stmt.name: f'{walker.enclosing_qualname(node)}'
                                   f'{"." if walker.enclosing_qualname(node) else ""}'
                                   f'{node.name}.{stmt.name}'
                        for stmt in node.body
                        if isinstance(stmt, (ast.FunctionDef,
                                             ast.AsyncFunctionDef))}
                    bases = [b for b in
                             (walker.dotted_name(e) for e in node.bases)
                             if b is not None]
                    self._classes.setdefault(node.name, []).append(
                        (src.rel, methods, bases))
                    for b in bases:
                        self._subs.setdefault(
                            b.rsplit('.', 1)[-1], set()).add(
                            node.name)
                    for name, qn in methods.items():
                        self._methods.setdefault(name, []).append(
                            (src.rel, qn))
                elif isinstance(node, ast.Assign):
                    self._note_lock_assign(node, src, mod)
                    self._note_attr_type(node)

    def _note_lock_assign(self, node: ast.Assign,
                          src: core.SourceFile, mod: str) -> None:
        if not isinstance(node.value, ast.Call):
            return
        factory = walker.call_name(node.value)
        kind = _LOCK_FACTORIES.get(factory or '')
        if kind is None:
            return
        for target in node.targets:
            dotted = walker.dotted_name(target)
            if dotted is None:
                continue
            if dotted.startswith('self.'):
                cls = walker.enclosing_class(node)
                lock_id = (f'{cls.name}.{dotted[5:]}' if cls is not None
                           else dotted[5:])
            elif '.' not in dotted:
                lock_id = (f'{mod}.{dotted}'
                           if walker.enclosing_function(node) is None
                           else dotted)
            else:
                lock_id = base(dotted)
            self.universe[lock_id] = (kind, src.rel)

    def _note_attr_type(self, node: ast.Assign) -> None:
        if not (isinstance(node.value, ast.Call)
                and len(node.targets) == 1):
            return
        target = walker.dotted_name(node.targets[0])
        if (target is None or not target.startswith('self.')
                or target.count('.') != 1):
            return
        ctor = walker.call_name(node.value)
        if ctor is None:
            return
        cls_name = ctor.rsplit('.', 1)[-1]
        if not cls_name[:1].isupper():
            return
        owner = walker.enclosing_class(node)
        if owner is None:
            return
        self._attr_types[(owner.name, target[5:])] = cls_name

    def kind(self, lock: str) -> Optional[str]:
        """Lock kind ('Lock'/'RLock'/'Condition'/'asyncio') or None
        when unknown. Bare ids resolve only if every universe entry
        with that base agrees on the kind."""
        hit = self.universe.get(lock)
        if hit is not None:
            return hit[0]
        kinds = {k for l, (k, _) in self.universe.items()
                 if base(l) == base(lock)}
        return kinds.pop() if len(kinds) == 1 else None

    def declared_in(self, lock: str) -> Optional[str]:
        hit = self.universe.get(lock)
        return hit[1] if hit is not None else None

    def declared_rels(self, lock: str) -> Set[str]:
        """Every module that declares a lock matching ``lock`` — exact
        id, or ALL same-base declarations for a bare annotation name
        (`# holds: _lock` could be any `*._lock`; severity decisions
        must fail closed over the candidates)."""
        hit = self.universe.get(lock)
        if hit is not None and hit[1]:
            return {hit[1]}
        return {rel for l, (_k, rel) in self.universe.items()
                if rel and base(l) == base(lock)}

    def _known_lock(self, lock_id: str) -> bool:
        if lock_id == EVENT_LOOP:
            return True
        if lock_id in self.universe:
            return True
        return has_base(self.universe, lock_id)

    def qualify(self, dotted: str, info: walker.FuncInfo) -> str:
        """Map a held dotted expression to a lock id in ``info``'s
        context: ``self.X`` -> ``Class.X``; a bare module-level name
        -> ``module.X``; anything else -> bare attribute name."""
        parts = dotted.split('.')
        if parts[0] == 'self' and len(parts) == 2 and info.cls:
            cand = f'{info.cls}.{parts[1]}'
            if cand in self.universe:
                return cand
            # The class may inherit the lock from a base in another
            # module; keep the class-qualified id anyway so ORDER
            # nodes stay distinct per class.
            return cand
        if len(parts) == 1:
            mod = info.src.rel.rsplit('/', 1)[-1][:-3]
            cand = f'{mod}.{parts[0]}'
            if cand in self.universe:
                return cand
            return parts[0]
        return parts[-1]

    def held_at(self, node: ast.AST,
                info: walker.FuncInfo) -> List[Tuple[str, int]]:
        """Qualified lock ids lexically held at ``node`` (filtered to
        known locks / annotation names), in acquisition order."""
        out: List[Tuple[str, int]] = []
        for dotted, line in walker.held_lock_sites(node):
            lock_id = self.qualify(dotted, info)
            if self._known_lock(lock_id):
                out.append((lock_id, line))
        return out

    def _build_summaries(self) -> None:
        # Annotation names join the known-lock set so `# holds: foo`
        # on a lockless helper still matches `with self.foo:` sites.
        for key, info in self.funcs.items():
            summ = Summary()
            summ.is_async = isinstance(info.node, ast.AsyncFunctionDef)
            summ.annotations = frozenset(
                walker.holds_annotations(info.src, info.node))
            self.summaries[key] = summ
        for ann in {a for s in self.summaries.values()
                    for a in s.annotations}:
            if ann != EVENT_LOOP and not self._known_lock(ann):
                self.universe.setdefault(ann, ('unknown', ''))
        for key, info in self.funcs.items():
            self._summarize(key, info)

    def _summarize(self, key: FuncKey, info: walker.FuncInfo) -> None:
        summ = self.summaries[key]
        imports = walker.module_imports(info.src)
        ext_names = walker.import_bound_names(info.src)
        seen_acq: Set[Tuple[str, int]] = set()

        def resolve_lock(node: ast.AST, dotted: str) -> Optional[str]:
            aliases = walker.lock_aliases(
                walker.enclosing_function(node))
            head, _, rest = dotted.partition('.')
            if head in aliases:
                dotted = aliases[head] + (f'.{rest}' if rest else '')
            lock_id = self.qualify(dotted, info)
            return lock_id if self._known_lock(lock_id) else None

        for node in walker.walk_function_body(info.node):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                # Items acquire left to right: item i's held-before is
                # the outer context plus items 0..i-1 — NOT its later
                # siblings (a naive same-line scan would read
                # `with (a, b):` as both a->b and b->a, a fake cycle).
                outer = [l for l, _ in self.held_at(node, info)]
                sofar: List[str] = []
                for item in node.items:
                    for expr in walker._with_item_exprs(item):
                        dotted = walker.dotted_name(expr)
                        if dotted is None:
                            continue
                        lock_id = resolve_lock(node, dotted)
                        if (lock_id is None
                                or (lock_id, node.lineno) in seen_acq):
                            continue
                        seen_acq.add((lock_id, node.lineno))
                        summ.acquires.append(Acquire(
                            lock_id, node.lineno,
                            tuple(outer + sofar)))
                        sofar.append(lock_id)
            elif isinstance(node, ast.Call):
                cname = walker.call_name(node)
                if (isinstance(node.func, ast.Attribute)
                        and node.func.attr == 'acquire'
                        and cname is not None):
                    lock_id = resolve_lock(node,
                                           cname.rsplit('.', 1)[0])
                    if (lock_id is not None
                            and (lock_id, node.lineno) not in seen_acq):
                        seen_acq.add((lock_id, node.lineno))
                        # held_at excludes this acquire itself (its
                        # interval starts strictly after its line).
                        summ.acquires.append(Acquire(
                            lock_id, node.lineno,
                            tuple(l for l, _ in
                                  self.held_at(node, info))))
                targets = self._resolve_call(node, info, imports,
                                             ext_names)
                if targets:
                    held = frozenset(
                        l for l, _ in self.held_at(node, info))
                    if summ.is_async:
                        held = held | {EVENT_LOOP}
                    summ.calls.append(CallSite(
                        tuple(sorted(set(targets))), node.lineno,
                        held))
            elif (isinstance(node, ast.Attribute)
                  and isinstance(node.ctx, ast.Load)
                  and isinstance(node.value, ast.Name)
                  and node.value.id == 'self'
                  and info.cls):
                # A bare method REFERENCE (`key=self._normalized_load`,
                # callbacks). Only a SYNCHRONOUS consumer (min/sorted/
                # map ...) runs the callee while the reference site's
                # locks are still held; a deferring consumer (executor
                # .submit, threading.Timer, storing it) runs it after
                # release — often on another thread — so its held set
                # must NOT flow into the callee (the soundness hole a
                # review caught: `with lock: pool.submit(self._flush)`
                # must not prove _flush locked). asyncio deferrals
                # keep event-loop confinement only.
                parent = getattr(node, '_sky_parent', None)
                if (isinstance(parent, ast.Call)
                        and parent.func is node):
                    continue   # a real call, handled above
                targets = self._resolve_in_class(info.cls, node.attr,
                                                 info.src.rel)
                targets += self._override_targets(
                    info.cls, node.attr, set(targets))
                if targets:
                    mode = self._ref_consumer_mode(node)
                    if mode == 'sync':
                        held = frozenset(
                            l for l, _ in self.held_at(node, info))
                        if summ.is_async:
                            held = held | {EVENT_LOOP}
                    elif mode == 'loop' and summ.is_async:
                        held = frozenset({EVENT_LOOP})
                    else:
                        held = frozenset()
                    summ.calls.append(CallSite(
                        tuple(sorted(set(targets))), node.lineno,
                        held, deferred=(mode != 'sync')))

    @staticmethod
    def _ref_consumer_mode(node: ast.AST) -> str:
        """How a method reference's consumer runs it: 'sync' (on this
        thread, locks still held), 'loop' (asyncio deferral — stays on
        the event loop, threading locks released), or 'deferred'
        (anything else: later and/or elsewhere)."""
        parent = getattr(node, '_sky_parent', None)
        if isinstance(parent, ast.keyword):
            parent = getattr(parent, '_sky_parent', None)
        if not isinstance(parent, ast.Call):
            return 'deferred'   # stored / returned: runs later
        consumer = walker.call_name(parent)
        if consumer is None:
            return 'deferred'
        base_name = consumer.rsplit('.', 1)[-1]
        if consumer in _SYNC_REF_CONSUMERS or (
                base_name in _SYNC_REF_CONSUMERS and '.' not in consumer):
            return 'sync'
        if base_name in _LOOP_DEFER_CONSUMERS:
            return 'loop'
        return 'deferred'

    # -- call resolution ---------------------------------------------------
    def _resolve_call(self, node: ast.Call, info: walker.FuncInfo,
                      imports: Dict[str, str],
                      ext_names: Optional[Set[str]] = None
                      ) -> List[FuncKey]:
        func = node.func
        # super().meth() -> base-class chain.
        if (isinstance(func, ast.Attribute)
                and isinstance(func.value, ast.Call)
                and walker.call_name(func.value) == 'super'
                and info.cls):
            return self._resolve_in_bases(info.cls, func.attr,
                                          info.src.rel)
        name = walker.dotted_name(func)
        if name is None:
            return []
        parts = name.split('.')
        mod_funcs = self.index.get(info.src.rel, {})
        if len(parts) == 1:
            # Bare name: scope chain innermost-out (SKY-TRACE's rule).
            prefix = info.qualname.split('.')
            for depth in range(len(prefix), -1, -1):
                cand = '.'.join(prefix[:depth] + [parts[0]])
                if cand in mod_funcs:
                    return [(info.src.rel, cand)]
            return []
        if parts[0] == 'self':
            if len(parts) == 2 and info.cls:
                hit = self._resolve_in_class(info.cls, parts[1],
                                             info.src.rel)
                hit += self._override_targets(info.cls, parts[1],
                                              set(hit))
                if hit:
                    return hit
            if len(parts) == 3 and info.cls:
                # self.attr.meth() with attr's class known from its
                # constructor assignment: resolve precisely.
                attr_cls = self._attr_types.get((info.cls, parts[1]))
                if attr_cls is not None:
                    hit = self._resolve_in_class(attr_cls, parts[2],
                                                 info.src.rel)
                    if hit:
                        return hit
            return self._duck(parts[-1],
                              parts[-2] if len(parts) >= 2 else None)
        # alias.func() / alias.Class.meth() through imports.
        target_rel = imports.get(parts[0])
        if target_rel is not None:
            rest = parts[1:]
            if len(rest) == 1 and rest[0] in self.index.get(
                    target_rel, {}):
                return [(target_rel, rest[0])]
            if len(rest) == 2:
                qn = '.'.join(rest)
                if qn in self.index.get(target_rel, {}):
                    return [(target_rel, qn)]
            return []
        # ClassName.meth() in the same module (or imported name).
        if len(parts) == 2 and parts[0] in self._classes:
            hit = self._resolve_in_class(parts[0], parts[1], None)
            if hit:
                return hit
        # A receiver that is an imported EXTERNAL module (os, np,
        # requests, ...) is not one of our objects — duck dispatch on
        # `os.path.exists()` would wire `GcsStore.exists` into the
        # config loader's call graph.
        if ext_names is not None and parts[0] in ext_names:
            return []
        # `f.g()` with f a local object falls through to duck
        # dispatch on the method name.
        return self._duck(parts[-1],
                          parts[-2] if len(parts) >= 2 else None)

    def _resolve_in_class(self, cls: str, meth: str,
                          prefer_rel: Optional[str]) -> List[FuncKey]:
        entries = self._classes.get(cls, [])
        if prefer_rel is not None:
            entries = sorted(entries,
                             key=lambda e: e[0] != prefer_rel)
        for rel, methods, bases in entries:
            if meth in methods:
                return [(rel, methods[meth])]
        # Walk base classes (first entry's bases).
        for rel, methods, bases in entries[:1]:
            for b in bases:
                b_cls = b.rsplit('.', 1)[-1]
                if b_cls != cls and b_cls in self._classes:
                    hit = self._resolve_in_bases(b_cls, meth, rel,
                                                 _self_ok=True)
                    if hit:
                        return hit
        return []

    def _resolve_in_bases(self, cls: str, meth: str,
                          rel: Optional[str],
                          _self_ok: bool = False) -> List[FuncKey]:
        """Resolve ``meth`` in ``cls``'s base classes (or ``cls``
        itself when ``_self_ok``)."""
        if _self_ok:
            return self._resolve_in_class(cls, meth, rel)
        for entry_rel, methods, bases in self._classes.get(cls, []):
            for b in bases:
                b_cls = b.rsplit('.', 1)[-1]
                if b_cls != cls and b_cls in self._classes:
                    hit = self._resolve_in_class(b_cls, meth,
                                                 entry_rel)
                    if hit:
                        return hit
        return []

    def _override_targets(self, cls: str, meth: str,
                          have: Set[FuncKey],
                          depth: int = 4) -> List[FuncKey]:
        """Virtual dispatch: overrides of ``meth`` in transitive
        subclasses of ``cls`` (bounded depth)."""
        out: List[FuncKey] = []
        frontier = {cls}
        seen = {cls}
        for _ in range(depth):
            nxt: Set[str] = set()
            for c in frontier:
                for sub in self._subs.get(c, ()):
                    if sub in seen:
                        continue
                    seen.add(sub)
                    nxt.add(sub)
                    for rel, methods, _bases in self._classes.get(
                            sub, []):
                        if meth in methods:
                            key = (rel, methods[meth])
                            if key not in have:
                                have.add(key)
                                out.append(key)
            frontier = nxt
            if not frontier:
                break
        return out

    def _duck(self, meth: str,
              receiver: Optional[str] = None) -> List[FuncKey]:
        """Duck dispatch: every class method named ``meth`` in the
        scanned set, unless the name is a generic verb or defined too
        widely. This is how `self._sched.pop_next()` reaches every
        scheduler policy and `pool.submit()` reaches the engine.

        When the receiver's name is descriptive (``self._sched.…``,
        ``breaker.…``) and matches a strict subset of the candidate
        classes, dispatch narrows to that subset — `sched_snapshot`'s
        ``self._sched.snapshot()`` must not wire the engine lock into
        ``CircuitBreaker.snapshot``."""
        if meth in _DUCK_DENY or meth.startswith('__'):
            return []
        candidates = self._methods.get(meth, [])
        if not candidates or len(candidates) > _DUCK_LIMIT:
            return []
        hint = (receiver or '').strip('_').lower()
        if len(hint) >= 4:
            hinted = [
                k for k in candidates
                if hint in k[1].rsplit('.', 2)[-2].lower()]
            if hinted:
                return hinted
        return list(candidates)

    # -- fixpoints ---------------------------------------------------------
    def _entry_locks(self, key: FuncKey) -> Set[str]:
        summ = self.summaries[key]
        out = set(self.may_entry.get(key, {}))
        out.update(summ.annotations)
        if summ.is_async:
            out.add(EVENT_LOOP)
        return out

    def _fixpoint_may(self) -> None:
        for key, summ in self.summaries.items():
            self.may_entry[key] = {a: None for a in summ.annotations}
        work = collections.deque(self.summaries)
        while work:
            key = work.popleft()
            summ = self.summaries[key]
            entry = self._entry_locks(key)
            for call in summ.calls:
                # A deferred callback does not inherit its creator's
                # lock context — only the (already-stripped) site held
                # set crosses the edge, never the caller's entry set.
                effective = (set(call.held) if call.deferred
                             else set(call.held) | entry)
                for tgt in call.targets:
                    m = self.may_entry.get(tgt)
                    if m is None:
                        continue
                    added = False
                    for lock in effective:
                        if lock not in m:
                            m[lock] = (key, call.line,
                                       lock in call.held)
                            added = True
                    if added:
                        work.append(tgt)

    def _fixpoint_must(self) -> None:
        TOP = None   # sentinel: not yet constrained (= universe)
        must: Dict[FuncKey, Optional[FrozenSet[str]]] = {}
        for key, summ in self.summaries.items():
            extra = ({EVENT_LOOP} if summ.is_async else set())
            if not self.in_edges.get(key):
                must[key] = frozenset(summ.annotations | extra)
            else:
                must[key] = TOP
        # Monotone-decreasing iteration from TOP: a caller leaving TOP
        # adds an intersection member (shrinks), a caller's must-set
        # shrinking shrinks its contribution — so plain recompute-
        # until-stable terminates in the finite lock lattice.
        changed = True
        while changed:
            changed = False
            for key in self.summaries:
                edges = self.in_edges.get(key)
                if not edges:
                    continue
                contribs: List[Set[str]] = []
                for e in edges:
                    if e.deferred:
                        # The callback runs later/elsewhere: the
                        # caller's must-set and annotations say
                        # nothing about the callee's entry context.
                        contribs.append(set(e.held))
                        continue
                    caller_must = must.get(e.caller)
                    if caller_must is TOP:
                        continue   # optimistic: unconstrained yet
                    caller_ann = (self.summaries[e.caller].annotations
                                  if e.caller in self.summaries
                                  else frozenset())
                    contribs.append(set(e.held) | set(caller_must)
                                    | set(caller_ann))
                if not contribs:
                    continue
                new: Set[str] = set.intersection(*contribs)
                if self.summaries[key].is_async:
                    new.add(EVENT_LOOP)
                new |= set(self.summaries[key].annotations)
                frozen = frozenset(new)
                if must[key] is TOP or frozen != must[key]:
                    must[key] = frozen
                    changed = True
        for key, val in must.items():
            if val is TOP:
                val = frozenset(self.summaries[key].annotations)
            self.must_entry[key] = val

    # -- chain reporting ---------------------------------------------------
    def qualname(self, key: FuncKey) -> str:
        return key[1]

    def holding_chain(self, key: FuncKey, lock: str,
                      limit: int = 8) -> List[str]:
        """Why might ``lock`` be held at ``key``'s entry — the caller
        chain from the acquiring frame down to ``key``."""
        names = [self.qualname(key)]
        cur = key
        for _ in range(limit):
            prov = self.may_entry.get(cur, {}).get(lock)
            if prov is None:
                break
            caller, _line, lexical = prov
            names.append(self.qualname(caller))
            if lexical:
                break
            cur = caller
        return list(reversed(names))

    def unlocked_chain(self, key: FuncKey, lock: str,
                       limit: int = 8) -> List[str]:
        """An example call chain reaching ``key`` on which ``lock`` is
        NOT held — the witness for a must-hold violation."""
        path = [key]
        cur = key
        seen = {key}
        for _ in range(limit):
            edges = self.in_edges.get(cur, [])
            pick = None
            for e in sorted(edges, key=lambda e: (e.caller, e.line)):
                if e.caller in seen or e.caller not in self.summaries:
                    continue
                caller_locks = set(e.held)
                if not e.deferred:
                    caller_locks |= set(self.must_entry.get(
                        e.caller, frozenset()))
                    caller_locks |= set(self.summaries[
                        e.caller].annotations)
                if not has_base(caller_locks, lock):
                    pick = e
                    break
            if pick is None:
                break
            path.append(pick.caller)
            seen.add(pick.caller)
            cur = pick.caller
        return [self.qualname(k) for k in reversed(path)]


# -- memoization ------------------------------------------------------------

_MEMO: 'collections.OrderedDict[Tuple, LockFlow]' = (
    collections.OrderedDict())
_MEMO_LIMIT = 4


def analyze(files: Sequence[core.SourceFile]) -> LockFlow:
    """Memoized whole-set analysis. SourceFile objects are cached by
    (mtime, size) in core.load_files, so object identity doubles as a
    content signature for the incremental path."""
    sig = tuple(sorted((s.rel, id(s)) for s in files))
    flow = _MEMO.get(sig)
    if flow is None:
        flow = LockFlow(files)
        _MEMO[sig] = flow
        while len(_MEMO) > _MEMO_LIMIT:
            _MEMO.popitem(last=False)
    else:
        _MEMO.move_to_end(sig)
    return flow


def clear_memo() -> None:
    _MEMO.clear()
