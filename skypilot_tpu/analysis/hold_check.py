"""SKY-HOLD: no blocking operations while a lock is held.

Every thread that wants the lock pays for whatever the holder does
under it. The engine's ``_lock`` is taken by HTTP handler threads on
every submit/cancel/metrics call — holding it across a device
readback turns one slow request into a stalled step loop; holding ANY
threading lock across ``await`` parks the event loop's other
coroutines behind a mutex that only a *thread* can release (the
classic async deadlock).

Sinks, found lexically AND transitively (a helper that sleeps is just
as blocking when its caller holds the lock three frames up —
lockflow's MAY-entry sets carry the held locks down the call graph):

===============  ========================================================
``await``        any Await expression while a *threading* lock is held
sleep            ``time.sleep``
net              ``requests.*``, ``urllib.request.urlopen``,
                 ``socket.create_connection``
subprocess       ``subprocess.run/call/check_*``, ``os.system``
device-sync      ``.block_until_ready()``, ``jax.device_get``,
                 ``np.asarray`` / ``numpy.asarray`` (the engine's
                 readback sync point), ``.item()``, ``.tolist()``
file-io          ``open()`` / ``io.open()``          (warn tier)
event-wait       ``.wait()`` on events/conditions    (warn tier)
===============  ========================================================

Severity tiers: ``await``/sleep/net/subprocess are hard errors under
any lock. Device-sync is a hard error when the held lock is declared
in ``infer/`` (the engine hot path — the exact "readback under
``_lock``" stall ROADMAP's p99 numbers point at) and a warning
elsewhere. File I/O and event waits are warnings: bounded local
operations that still deserve an audit. Warnings beyond the allowlist
cap are reported but do not fail the gate (``Report.ok`` counts only
error-severity offenders); the allowlist ratchet counts both.

asyncio locks are exempt everywhere (holding one across ``await`` is
their purpose); the ``event-loop`` pseudo-lock is confinement, not a
mutex, and never counts as held.
"""
from __future__ import annotations

import ast
from typing import Iterable, List, Optional, Sequence, Set, Tuple

from skypilot_tpu.analysis import core
from skypilot_tpu.analysis import lockflow
from skypilot_tpu.analysis import walker

_SINK_CALLS = {
    'time.sleep': ('sleep', 'error'),
    'urllib.request.urlopen': ('net', 'error'),
    'socket.create_connection': ('net', 'error'),
    'subprocess.run': ('subprocess', 'error'),
    'subprocess.call': ('subprocess', 'error'),
    'subprocess.check_call': ('subprocess', 'error'),
    'subprocess.check_output': ('subprocess', 'error'),
    'subprocess.Popen': ('subprocess', 'error'),
    'os.system': ('subprocess', 'error'),
    'jax.device_get': ('device-sync', 'device'),
    'np.asarray': ('device-sync', 'device'),
    'numpy.asarray': ('device-sync', 'device'),
    'open': ('file-io', 'warn'),
    'io.open': ('file-io', 'warn'),
}
_NET_PREFIXES = ('requests.',)
_SINK_METHODS = {
    'block_until_ready': ('device-sync', 'device'),
    'item': ('device-sync', 'device'),
    'tolist': ('device-sync', 'device'),
    'wait': ('event-wait', 'warn'),
}


class HoldChecker(core.Checker):
    code = 'SKY-HOLD'
    title = 'no blocking operations while a lock is held'

    def check(self, files: Sequence[core.SourceFile],
              ctx: core.RunContext) -> Iterable[core.Finding]:
        flow = lockflow.analyze(files)
        for key in sorted(flow.summaries):
            info = flow.funcs[key]
            entry = {
                l for l in flow._entry_locks(key)
                if l != lockflow.EVENT_LOOP
                and flow.kind(l) != 'asyncio'}
            yield from self._check_function(flow, info, entry)

    def _check_function(self, flow: 'lockflow.LockFlow', info,
                        entry: Set[str]) -> Iterable[core.Finding]:
        for node in walker.walk_function_body(info.node):
            sink = self._classify(node)
            if sink is None:
                continue
            label, tier = sink
            lexical = {l for l, _ in flow.held_at(node, info)
                       if flow.kind(l) != 'asyncio'}
            held = lexical | entry
            if not held:
                continue
            severity = self._severity(flow, tier, held)
            locks = sorted(held)
            primary = next((l for l in locks if l in lexical),
                           locks[0])
            chain: Optional[Tuple[str, ...]] = None
            if primary not in lexical:
                chain = tuple(flow.holding_chain(info.key, primary))
            via = (f' (held on the call chain '
                   f'{" -> ".join(chain)})' if chain else '')
            yield core.Finding(
                self.code, info.src.rel,
                getattr(node, 'lineno', info.node.lineno),
                f'{label} [{severity}]: {self._describe(node)} while '
                f'holding {", ".join(locks)} in {info.qualname}{via} '
                f'— every waiter on the lock stalls behind it; '
                f'snapshot under the lock, then release before '
                f'blocking',
                severity=severity, chain=chain)

    @staticmethod
    def _classify(node: ast.AST) -> Optional[Tuple[str, str]]:
        if isinstance(node, ast.Await):
            return ('await', 'error')
        if not isinstance(node, ast.Call):
            return None
        # Method sinks match on the attribute alone, BEFORE the dotted
        # name is required: `self._pairs[0].block_until_ready()` has a
        # Subscript receiver that dotted_name cannot render, and it is
        # exactly the readback shape this checker exists for. `wait`
        # is the one arg-sensitive sink: `q.wait()` blocks forever,
        # `ev.wait(0.05)` is a bounded nap.
        if (isinstance(node.func, ast.Attribute)
                and node.func.attr in _SINK_METHODS):
            if node.func.attr != 'wait' or not (node.args
                                                or node.keywords):
                return _SINK_METHODS[node.func.attr]
        name = walker.call_name(node)
        if name is None:
            return None
        hit = _SINK_CALLS.get(name)
        if hit is not None:
            return hit
        if name.startswith(_NET_PREFIXES):
            return ('net', 'error')
        return None

    @staticmethod
    def _severity(flow: 'lockflow.LockFlow', tier: str,
                  held: Set[str]) -> str:
        if tier == 'device':
            # Fail closed: a bare held name (`# holds: _lock`) matches
            # every same-base declaration — if ANY candidate lives in
            # infer/, treat the readback as the engine-stall case.
            for lock in held:
                if any(rel.startswith('infer/')
                       for rel in flow.declared_rels(lock)):
                    return 'error'
            return 'warn'
        return tier

    @staticmethod
    def _describe(node: ast.AST) -> str:
        if isinstance(node, ast.Await):
            return 'await'
        name = walker.call_name(node)
        if name:
            return f'{name}()'
        if isinstance(node.func, ast.Attribute):
            return f'.{node.func.attr}()'
        return 'call'
