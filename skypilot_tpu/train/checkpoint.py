"""Orbax checkpointing: the managed-jobs checkpoint/resume convention.

The reference has no model checkpointing in-tree; its recovery pattern is
"mount a bucket, write checkpoints there, re-run resumes from the bucket"
(reference llm/llama-3_1-finetuning/lora.yaml:27-31; SURVEY.md §5). This
module is that pattern made concrete for JAX: async Orbax saves into a
directory (typically a gcsfuse-mounted bucket — ``data/storage.py``), and
``restore_or_init`` is what recovered jobs call on startup.
"""
from __future__ import annotations

import os
from typing import Any, Optional

import jax
import orbax.checkpoint as ocp

DEFAULT_CHECKPOINT_DIR_ENV = 'SKY_TPU_CHECKPOINT_DIR'


class CheckpointManager:
    """Thin wrapper over orbax CheckpointManager with async saves."""

    def __init__(self, directory: str, *, max_to_keep: int = 3,
                 save_interval_steps: int = 1):
        self.directory = os.path.abspath(os.path.expanduser(directory))
        os.makedirs(self.directory, exist_ok=True)
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                save_interval_steps=save_interval_steps,
                enable_async_checkpointing=True,
            ))

    def save(self, step: int, state: Any, *, force: bool = False) -> bool:
        return self._mgr.save(step, args=ocp.args.StandardSave(state),
                              force=force)

    def restore(self, step: Optional[int] = None,
                target: Optional[Any] = None) -> Any:
        step = self.latest_step() if step is None else step
        if step is None:
            raise FileNotFoundError(
                f'No checkpoint under {self.directory}')
        if target is not None:
            target_struct = jax.tree_util.tree_map(
                ocp.utils.to_shape_dtype_struct, target)
            return self._mgr.restore(
                step, args=ocp.args.StandardRestore(target_struct))
        return self._mgr.restore(step)

    def restore_to_host(self, target: Any,
                        step: Optional[int] = None) -> Any:
        """Restore onto the HOST (cpu backend), not the accelerator.

        The int8 serving path needs this: an 8B bf16 checkpoint (16 GB)
        cannot first land on the 16 GB chip it is being quantized to
        fit — it restores into host RAM and quantizes leaf-by-leaf onto
        the device (ops/quant.py quantize_params_transfer). ``target``
        is a concrete or abstract pytree giving shapes/dtypes."""
        step = self.latest_step() if step is None else step
        if step is None:
            raise FileNotFoundError(
                f'No checkpoint under {self.directory}')
        cpu = jax.local_devices(backend='cpu')[0]
        sharding = jax.sharding.SingleDeviceSharding(cpu)
        target_struct = jax.tree_util.tree_map(
            lambda l: jax.ShapeDtypeStruct(l.shape, l.dtype,
                                           sharding=sharding),
            jax.tree_util.tree_map(ocp.utils.to_shape_dtype_struct,
                                   target))
        return self._mgr.restore(
            step, args=ocp.args.StandardRestore(target_struct))

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def wait(self) -> None:
        """Block until async saves are durable (call before teardown)."""
        self._mgr.wait_until_finished()

    def close(self) -> None:
        self._mgr.close()


def restore_or_init(directory: str, init_fn, *,
                    target: Optional[Any] = None) -> tuple:
    """The resume convention: restore the latest checkpoint if one exists,
    else initialize fresh. Returns (state, restored: bool)."""
    mgr = CheckpointManager(directory)
    step = mgr.latest_step()
    if step is None:
        return init_fn(), False
    state = mgr.restore(step, target=target if target is not None
                        else init_fn())
    return state, True
