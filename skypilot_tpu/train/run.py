"""Training entrypoint: ``python -m skypilot_tpu.train.run``.

The runnable behind BASELINE.md configs #3 (multi-host FSDP finetune) and
#5 (preemptible pretrain with auto-recovery). One binary covers
single-chip, single-slice multi-host (``jax.distributed`` env injected by
the runtime agent, runtime/distributed_env.py), and checkpoint/resume
(Orbax into a mounted bucket — the managed-jobs recovery convention).

    python -m skypilot_tpu.train.run --model llama-350m --steps 100 \
        --batch 8 --seq 2048 --fsdp 8 --checkpoint-dir gs://bkt/ckpt

Data is synthetic-by-default (throughput/recovery benchmarking); a real
corpus plugs in by replacing ``synthetic_batch`` with a data iterator.
"""
from __future__ import annotations

import argparse
import logging
import os
import sys
import time

logger = logging.getLogger(__name__)

MODELS = {
    'llama-tiny': ('llama', 'tiny'),
    'llama-350m': ('llama', 'bench_350m'),
    'llama-8b': ('llama', 'llama3_8b'),
    'llama-70b': ('llama', 'llama3_70b'),
    'moe-tiny': ('moe', 'tiny'),
    'moe-8x7b': ('moe', 'mixtral_8x7b'),
}


def _maybe_init_distributed() -> None:
    """Join the slice process group when the agent injected the env."""
    import jax

    from skypilot_tpu.runtime import distributed_env
    num = int(os.environ.get('JAX_NUM_PROCESSES', '1'))
    if num > 1:
        jax.distributed.initialize()   # env-driven, distributed_env.py
        logger.info('jax.distributed up: process %s/%s',
                    os.environ.get('JAX_PROCESS_ID'), num)
    del distributed_env


def main(argv=None) -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument('--model', default='llama-350m',
                        choices=sorted(MODELS))
    parser.add_argument('--steps', type=int, default=100)
    parser.add_argument('--batch', type=int, default=8,
                        help='Global batch size.')
    parser.add_argument('--seq', type=int, default=2048)
    parser.add_argument('--lr', type=float, default=3e-4)
    parser.add_argument('--dp', type=int, default=1)
    parser.add_argument('--fsdp', type=int, default=0,
                        help='0 = all remaining devices.')
    parser.add_argument('--tp', type=int, default=1)
    parser.add_argument('--checkpoint-dir', default=os.environ.get(
        'SKY_TPU_CHECKPOINT_DIR'))
    parser.add_argument('--checkpoint-every', type=int, default=50)
    parser.add_argument('--log-every', type=int, default=10)
    args = parser.parse_args(argv)
    logging.basicConfig(
        level=logging.INFO,
        format='%(asctime)s %(levelname)s %(name)s: %(message)s')

    _maybe_init_distributed()
    import jax
    import jax.numpy as jnp

    from skypilot_tpu.models import llama
    from skypilot_tpu.parallel import mesh as mesh_lib
    from skypilot_tpu.parallel import sharding as sharding_lib
    from skypilot_tpu.train import trainer

    family, preset = MODELS[args.model]
    if family != 'llama':
        raise SystemExit(f'--model {args.model}: the MoE trainer entry '
                         f'lands with the MoE train-step factory; use '
                         f'llama-* presets here for now')
    config = getattr(llama.LlamaConfig, preset)(max_seq_len=args.seq)

    n = len(jax.devices())
    fsdp = args.fsdp or n // (args.dp * args.tp)
    mesh = mesh_lib.make_mesh(dp=args.dp, fsdp=fsdp, tp=args.tp)
    logger.info('devices=%d mesh dp=%d fsdp=%d tp=%d model=%s (%.0fM)',
                n, args.dp, fsdp, args.tp, args.model,
                config.num_params / 1e6)

    opt = trainer.make_optimizer(learning_rate=args.lr,
                                 total_steps=args.steps)
    step_fn = trainer.make_train_step(config, opt, mesh=mesh)

    start_step = 0
    if args.checkpoint_dir:
        from skypilot_tpu.train import checkpoint as ckpt_lib
        mgr = ckpt_lib.CheckpointManager(
            args.checkpoint_dir, save_interval_steps=args.checkpoint_every)
        state, restored = ckpt_lib.restore_or_init(
            args.checkpoint_dir,
            lambda: trainer.init_train_state(config, jax.random.PRNGKey(0),
                                             opt))
        if restored:
            start_step = int(state.step)
            logger.info('resumed from checkpoint at step %d', start_step)
    else:
        mgr = None
        state = trainer.init_train_state(config, jax.random.PRNGKey(0),
                                         opt)
    state = trainer.shard_train_state(state, mesh)

    batch = trainer.synthetic_batch(config, args.batch, args.seq,
                                    jax.random.PRNGKey(1))
    bshard = sharding_lib.batch_sharding(mesh)
    batch = {k: jax.device_put(v, bshard) for k, v in batch.items()}

    tokens_per_step = args.batch * args.seq
    t_last = time.perf_counter()
    for step in range(start_step, args.steps):
        state, metrics = step_fn(state, batch)
        if (step + 1) % args.log_every == 0 or step + 1 == args.steps:
            loss = float(metrics['loss'])
            dt = time.perf_counter() - t_last
            t_last = time.perf_counter()
            tps = tokens_per_step * args.log_every / dt
            logger.info('step %d/%d loss=%.4f tokens/s=%.0f',
                        step + 1, args.steps, loss, tps)
            if not jnp.isfinite(metrics['loss']):
                logger.error('non-finite loss; aborting')
                sys.exit(1)
        if mgr is not None:
            mgr.save(step + 1, jax.device_get(state))
    if mgr is not None:
        mgr.wait()
        mgr.close()
    logger.info('done: %d steps', args.steps)


if __name__ == '__main__':
    main()
