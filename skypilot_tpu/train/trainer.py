"""Sharded training loop pieces: TrainState + jitted train step factory.

The compute-side counterpart of BASELINE.md's finetune configs. Everything
is mesh-agnostic: pass any Mesh (1 chip, v5e-8, v5p pod, or the CPU test
mesh) and the same code runs — the TPU-first property the whole framework
is built around.
"""
from __future__ import annotations

import dataclasses
import functools
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from skypilot_tpu.models import llama
from skypilot_tpu.parallel import sharding as sharding_lib


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class TrainState:
    step: jnp.ndarray
    params: Any
    opt_state: Any


def make_optimizer(learning_rate: float = 3e-4,
                   weight_decay: float = 0.1,
                   warmup_steps: int = 100,
                   total_steps: int = 10_000,
                   grad_clip: float = 1.0,
                   mu_dtype: Optional[str] = None
                   ) -> optax.GradientTransformation:
    """AdamW + cosine schedule. ``mu_dtype='bfloat16'`` halves the
    first-moment memory — the difference between fitting a ~1B model on
    one v5e chip and OOMing (nu stays fp32 for numerics)."""
    schedule = optax.warmup_cosine_decay_schedule(
        0.0, learning_rate, warmup_steps, max(total_steps, warmup_steps + 1))
    return optax.chain(
        optax.clip_by_global_norm(grad_clip),
        optax.adamw(schedule, b1=0.9, b2=0.95, weight_decay=weight_decay,
                    mu_dtype=mu_dtype),
    )


def init_train_state(config: llama.LlamaConfig, key: jax.Array,
                     optimizer: optax.GradientTransformation) -> TrainState:
    params = llama.init_params(config, key)
    return TrainState(step=jnp.zeros((), jnp.int32), params=params,
                      opt_state=optimizer.init(params))


def shard_train_state(state: TrainState, mesh: Mesh) -> TrainState:
    p_shard = sharding_lib.param_shardings(mesh, state.params)
    o_shard = sharding_lib.opt_state_shardings(mesh, state.opt_state,
                                               state.params)
    return TrainState(
        step=jax.device_put(state.step, NamedSharding(mesh, P())),
        params=sharding_lib.shard_pytree(state.params, p_shard),
        opt_state=sharding_lib.shard_pytree(state.opt_state, o_shard))


def make_train_step(config: llama.LlamaConfig,
                    optimizer: optax.GradientTransformation,
                    mesh: Optional[Mesh] = None):
    """Returns jitted (state, batch) -> (state, metrics).

    batch: {'tokens': [b, s] int32, 'targets': [b, s] int32,
            'mask': optional [b, s]}.
    Under a mesh, inputs/outputs carry NamedShardings and the state buffer
    is donated (in-place update on device).
    """

    def step_fn(state: TrainState,
                batch: Dict[str, jnp.ndarray]
                ) -> Tuple[TrainState, Dict[str, jnp.ndarray]]:
        def loss(params):
            return llama.loss_fn(config, params, batch['tokens'],
                                 batch['targets'], batch.get('mask'))

        loss_val, grads = jax.value_and_grad(loss)(state.params)
        updates, new_opt = optimizer.update(grads, state.opt_state,
                                            state.params)
        new_params = optax.apply_updates(state.params, updates)
        metrics = {
            'loss': loss_val,
            'grad_norm': optax.global_norm(grads),
            'step': state.step + 1,
        }
        return TrainState(step=state.step + 1, params=new_params,
                          opt_state=new_opt), metrics

    if mesh is None:
        return jax.jit(step_fn, donate_argnums=(0,))

    # Explicit shardings: params/opt as the rules say, batch over data axes,
    # metrics replicated.
    dummy_params_struct = jax.eval_shape(
        lambda: llama.init_params(config, jax.random.PRNGKey(0)))
    p_shard = sharding_lib.param_shardings(mesh, dummy_params_struct)
    o_struct = jax.eval_shape(lambda: optimizer.init(
        jax.tree_util.tree_map(jnp.zeros_like, dummy_params_struct)))
    o_shard = sharding_lib.opt_state_shardings(mesh, o_struct,
                                               dummy_params_struct)
    repl = NamedSharding(mesh, P())
    state_shard = TrainState(step=repl, params=p_shard, opt_state=o_shard)
    batch_shard = sharding_lib.batch_sharding(mesh)
    return jax.jit(
        step_fn,
        in_shardings=(state_shard,
                      {'tokens': batch_shard, 'targets': batch_shard}),
        out_shardings=(state_shard,
                       {'loss': repl, 'grad_norm': repl, 'step': repl}),
        donate_argnums=(0,))


def synthetic_batch(config: llama.LlamaConfig, batch_size: int,
                    seq_len: int, key: jax.Array) -> Dict[str, jnp.ndarray]:
    tokens = jax.random.randint(key, (batch_size, seq_len + 1), 0,
                                config.vocab_size, dtype=jnp.int32)
    return {'tokens': tokens[:, :-1], 'targets': tokens[:, 1:]}
