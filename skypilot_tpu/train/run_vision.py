"""Vision DDP entrypoint: ``python -m skypilot_tpu.train.run_vision``.

BASELINE.md config #2 (JAX ResNet DDP on v5e-8, replacing the
reference's examples/resnet_distributed_torch.yaml). Pure data parallel:
params replicated, batch sharded over every chip — one NamedSharding,
XLA emits the gradient all-reduce over ICI.
"""
from __future__ import annotations

import argparse
import logging
import os
import time

logger = logging.getLogger(__name__)


def main(argv=None) -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument('--model', default='resnet18',
                        choices=['tiny', 'resnet18', 'resnet50'])
    parser.add_argument('--steps', type=int, default=100)
    parser.add_argument('--batch', type=int, default=256,
                        help='Global batch size.')
    parser.add_argument('--image-size', type=int, default=224)
    parser.add_argument('--lr', type=float, default=0.1)
    parser.add_argument('--log-every', type=int, default=10)
    args = parser.parse_args(argv)
    logging.basicConfig(
        level=logging.INFO,
        format='%(asctime)s %(levelname)s %(name)s: %(message)s')

    if int(os.environ.get('JAX_NUM_PROCESSES', '1')) > 1:
        import jax
        jax.distributed.initialize()
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from skypilot_tpu.models import resnet

    config = getattr(resnet.ResNetConfig, args.model)()
    devices = np.array(jax.devices())
    mesh = Mesh(devices, ('dp',))
    repl = NamedSharding(mesh, P())
    data = NamedSharding(mesh, P('dp'))
    logger.info('DDP over %d devices, model=%s', len(devices),
                args.model)

    params = jax.device_put(
        resnet.init_params(config, jax.random.PRNGKey(0)), repl)
    opt = optax.sgd(optax.cosine_decay_schedule(args.lr, args.steps),
                    momentum=0.9, nesterov=True)
    opt_state = jax.device_put(opt.init(params), repl)

    @jax.jit
    def step_fn(params, opt_state, images, labels):
        loss, grads = jax.value_and_grad(
            lambda p: resnet.loss_fn(config, p, images, labels))(params)
        updates, opt_state = opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    key = jax.random.PRNGKey(1)
    s = args.image_size
    images = jax.device_put(
        jax.random.normal(key, (args.batch, s, s, 3), jnp.float32), data)
    labels = jax.device_put(
        jax.random.randint(key, (args.batch,), 0, config.num_classes),
        data)

    t_last = time.perf_counter()
    for step in range(args.steps):
        params, opt_state, loss = step_fn(params, opt_state, images,
                                          labels)
        if (step + 1) % args.log_every == 0 or step + 1 == args.steps:
            dt = time.perf_counter() - t_last
            t_last = time.perf_counter()
            ips = args.batch * args.log_every / dt
            logger.info('step %d/%d loss=%.4f images/s=%.0f', step + 1,
                        args.steps, float(loss), ips)
    logger.info('done')


if __name__ == '__main__':
    main()
