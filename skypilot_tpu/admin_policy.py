"""Pluggable admin policy hook (reference sky/admin_policy.py).

Every launch passes its task through the configured policy, which may
mutate or reject it (reference applies it at sky/execution.py:252).
Configure with::

    admin_policy: mypkg.mymodule.MyPolicy

in the layered config; the class must implement
``validate_and_mutate(user_request) -> MutatedUserRequest``.
"""
from __future__ import annotations

import dataclasses
import importlib
from typing import Optional

from skypilot_tpu import config as config_lib
from skypilot_tpu import exceptions
from skypilot_tpu import task as task_lib


@dataclasses.dataclass
class UserRequest:
    task: task_lib.Task


@dataclasses.dataclass
class MutatedUserRequest:
    task: task_lib.Task


class AdminPolicy:
    """Base class: identity policy."""

    def validate_and_mutate(self,
                            user_request: UserRequest) -> MutatedUserRequest:
        return MutatedUserRequest(task=user_request.task)


def _load_policy() -> Optional[AdminPolicy]:
    path = config_lib.get_nested(('admin_policy',))
    if not path:
        return None
    module_name, _, cls_name = str(path).rpartition('.')
    try:
        cls = getattr(importlib.import_module(module_name), cls_name)
        policy = cls()
    except (ImportError, AttributeError) as e:
        raise exceptions.InvalidTaskError(
            f'admin_policy {path!r} could not be loaded: {e}') from e
    if not isinstance(policy, AdminPolicy):
        raise exceptions.InvalidTaskError(
            f'admin_policy {path!r} is not an AdminPolicy subclass')
    return policy


def apply(task: task_lib.Task) -> task_lib.Task:
    policy = _load_policy()
    if policy is None:
        return task
    return policy.validate_and_mutate(UserRequest(task=task)).task
