"""Declarative per-cloud capability flags.

Counterpart of the reference's ``CloudImplementationFeatures`` + ``Cloud``
base (reference sky/clouds/cloud.py:40-105,158): the optimizer rejects
infeasible (cloud, feature) combinations declaratively instead of
scattering per-call checks. A task's required features are derived from
its spec (spot, multislice, ports, mounts, ...); a cloud is a launch
candidate only when it supports all of them, and the mismatch message
names exactly which feature ruled each cloud out.

The flag tables live here (4 clouds today) so adding a cloud is one dict
entry plus a provisioner package — no optimizer edits.
"""
from __future__ import annotations

import enum
from typing import TYPE_CHECKING, Dict, FrozenSet, List

from skypilot_tpu import exceptions

if TYPE_CHECKING:
    from skypilot_tpu import task as task_lib


class Feature(str, enum.Enum):
    """Things a task/operation may require of a cloud."""
    STOP = 'stop'                       # cluster can stop + restart
    AUTOSTOP = 'autostop'               # cluster can stop ITSELF when idle
    SPOT = 'spot'                       # preemptible capacity exists
    MULTISLICE = 'multislice'           # num_slices > 1 (DCN gangs)
    STORAGE_MOUNTING = 'storage_mounting'   # bucket FUSE mounts
    OPEN_PORTS = 'open_ports'           # expose ports to the network
    VOLUMES = 'volumes'                 # attachable block volumes
    HOST_CONTROLLERS = 'host_controllers'   # can run jobs/serve controllers


# What each provider actually implements (kept in lockstep with
# provision/<cloud>/instance.py — the unit tests assert the load-bearing
# entries against provider behavior).
CLOUD_FEATURES: Dict[str, FrozenSet[Feature]] = {
    'gcp': frozenset({
        Feature.STOP, Feature.AUTOSTOP, Feature.SPOT, Feature.MULTISLICE,
        Feature.STORAGE_MOUNTING, Feature.VOLUMES,
        Feature.HOST_CONTROLLERS,
        # OPEN_PORTS: real VPC firewall rules targeted at the slice's
        # network tag (provision/gcp/instance.py open_ports) — external
        # exposure, not just intra-VPC reachability.
        Feature.OPEN_PORTS,
    }),
    'local': frozenset({
        Feature.STOP, Feature.AUTOSTOP, Feature.SPOT, Feature.MULTISLICE,
        Feature.STORAGE_MOUNTING, Feature.OPEN_PORTS, Feature.VOLUMES,
        Feature.HOST_CONTROLLERS,
    }),
    'kubernetes': frozenset({
        # stop = scale-to-zero (provision/k8s/instance.py:193).
        Feature.STOP, Feature.STORAGE_MOUNTING,
        Feature.HOST_CONTROLLERS,
        # SPOT: GKE spot node pools (render_slice use_spot toleration +
        # nodeSelector); OPEN_PORTS: Service exposure (open_ports);
        # VOLUMES: k8s-pvc PersistentVolumeClaims; MULTISLICE: one
        # StatefulSet per slice with per-slice selectors and slice-aware
        # agent configs (run_instances/_bootstrap_agents).
        Feature.SPOT, Feature.OPEN_PORTS, Feature.VOLUMES,
        Feature.MULTISLICE,
        # NOT AUTOSTOP: the in-pod agent cannot scale its own
        # StatefulSet without RBAC the manifests do not grant.
    }),
    'ssh': frozenset({
        # Bare metal: hosts are sunk cost; stop = stop the agents.
        Feature.STOP, Feature.AUTOSTOP, Feature.STORAGE_MOUNTING,
        Feature.HOST_CONTROLLERS,
    }),
    'slurm': frozenset({
        # stop = scancel the allocation, start = resubmit
        # (provision/slurm/instance.py); intra-cluster network is open.
        Feature.STOP, Feature.STORAGE_MOUNTING, Feature.OPEN_PORTS,
        Feature.HOST_CONTROLLERS,
    }),
}


def features_of(cloud: str) -> FrozenSet[Feature]:
    return CLOUD_FEATURES.get(cloud, frozenset())


def required_features(task: 'task_lib.Task',
                      resources=None) -> FrozenSet[Feature]:
    """Features this task's spec demands of whatever cloud runs it.

    `resources` overrides the task's base resources — any_of failover
    alternatives may flip spot/ports/num_slices, so the caller must gate
    each alternative against ITS OWN feature set, not the base one.
    """
    needed = set()
    res = resources if resources is not None else task.resources
    if res.use_spot:
        needed.add(Feature.SPOT)
    if res.num_slices > 1:
        needed.add(Feature.MULTISLICE)
    if res.ports:
        needed.add(Feature.OPEN_PORTS)
    if res.autostop is not None and res.autostop.enabled:
        needed.add(Feature.AUTOSTOP)
    if task.volumes:
        needed.add(Feature.VOLUMES)
    if task.storage_mounts or any(
            _is_bucket(src) for src in (task.file_mounts or {}).values()):
        needed.add(Feature.STORAGE_MOUNTING)
    return frozenset(needed)


def _is_bucket(src: str) -> bool:
    from skypilot_tpu.data import storage as storage_lib
    return storage_lib.is_bucket_url(src)


def unsupported(cloud: str, needed: FrozenSet[Feature]) -> List[Feature]:
    return sorted(needed - features_of(cloud), key=lambda f: f.value)


def check_features(cloud: str, needed: FrozenSet[Feature]) -> None:
    """Raise with the exact blocking features (reference
    check_features_are_supported)."""
    missing = unsupported(cloud, needed)
    if missing:
        raise exceptions.ResourcesMismatchError(
            f'cloud {cloud!r} does not support: '
            f'{[f.value for f in missing]}')
