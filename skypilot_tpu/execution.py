"""Launch/exec stage machine — the engine's entrypoints.

Counterpart of the reference's ``sky/execution.py`` (``Stage`` enum :48,
``_execute`` :158, ``launch`` :602, ``exec`` :825). Stages:

    OPTIMIZE → PROVISION → SYNC_WORKDIR → SYNC_FILE_MOUNTS → SETUP → EXEC
    (→ DOWN for autodown)

Cluster reuse: launching onto an existing UP cluster skips PROVISION if the
cluster satisfies the request (``Resources.less_demanding_than``); `exec`
skips straight to SYNC_WORKDIR+EXEC (reference exec semantics).
The whole plan runs under the per-cluster lock (planner-under-lock,
reference sky/execution.py:469-487).
"""
from __future__ import annotations

import enum
import logging
import uuid
from typing import Any, Dict, List, Optional, Tuple

from skypilot_tpu import admin_policy as admin_policy_lib
from skypilot_tpu import backend as backend_lib
from skypilot_tpu import catalog
from skypilot_tpu import exceptions
from skypilot_tpu import optimizer as optimizer_lib
from skypilot_tpu import resources as resources_lib
from skypilot_tpu import state
from skypilot_tpu import task as task_lib
from skypilot_tpu.observability import trace
from skypilot_tpu.provision.common import ClusterInfo
from skypilot_tpu import usage
from skypilot_tpu.utils import common
from skypilot_tpu.utils import locks
from skypilot_tpu.utils import timeline

logger = logging.getLogger(__name__)


class Stage(enum.Enum):
    OPTIMIZE = 'OPTIMIZE'
    PROVISION = 'PROVISION'
    SYNC_WORKDIR = 'SYNC_WORKDIR'
    SYNC_FILE_MOUNTS = 'SYNC_FILE_MOUNTS'
    SETUP = 'SETUP'
    EXEC = 'EXEC'
    DOWN = 'DOWN'


def _generate_cluster_name() -> str:
    return f'sky-{uuid.uuid4().hex[:8]}'


def _existing_cluster_info(
        cluster_name: str,
        res: resources_lib.Resources) -> Optional[ClusterInfo]:
    """Return ClusterInfo if an UP cluster satisfies the request."""
    record = state.get_cluster(cluster_name)
    if record is None:
        return None
    if record['status'] != common.ClusterStatus.UP:
        raise exceptions.ClusterNotUpError(
            f'Cluster {cluster_name!r} is {record["status"].value}. '
            f'`sky-tpu start {cluster_name}` it first, or choose another '
            f'name.')
    existing = resources_lib.Resources.from_yaml_config(record['resources'])
    if not res.less_demanding_than(existing):
        raise exceptions.ResourcesMismatchError(
            f'Cluster {cluster_name!r} ({existing!r}) cannot satisfy the '
            f'requested {res!r}. Launch a new cluster or relax the request.')
    return ClusterInfo.from_dict(record['cluster_info'])


@usage.entrypoint(name='launch')
@timeline.event(name='execution.launch')
@trace.traced(name='execution.launch')
def launch(
    task: task_lib.Task,
    cluster_name: Optional[str] = None,
    *,
    backend: Optional[backend_lib.Backend] = None,
    optimize_target: optimizer_lib.OptimizeTarget =
        optimizer_lib.OptimizeTarget.COST,
    detach_run: bool = True,
    stages: Optional[List[Stage]] = None,
    quiet: bool = True,
    blocked_placements: Optional[List[Tuple[str, str]]] = None,
    avoid_placements: Optional[List[Tuple[str, str]]] = None,
    caller: Optional[Dict[str, Any]] = None,
) -> Tuple[int, ClusterInfo]:
    """Provision (or reuse) a cluster and run the task on it.

    Returns (job_id, cluster_info); job_id is -1 for run-less tasks.
    """
    task = admin_policy_lib.apply(task)
    # Private-workspace gate (reference workspaces/core.py:659
    # reject_request_for_unauthorized_workspace): the active workspace
    # must admit the launching identity. In API-server mode the worker
    # runs as the server's OS user, so the HTTP layer passes the
    # authenticated caller through `caller`; the local OS identity
    # applies only for direct/library use (caller=None).
    from skypilot_tpu import users as users_lib
    from skypilot_tpu import workspaces as workspaces_lib
    identity = (caller if caller is not None
                else users_lib.core.ensure_user())
    workspaces_lib.check_workspace_permission(
        identity, workspaces_lib.active_workspace())
    cluster_name = cluster_name or _generate_cluster_name()
    backend = backend or backend_lib.TpuVmBackend()
    run_stages = stages or [
        Stage.OPTIMIZE, Stage.PROVISION, Stage.SYNC_WORKDIR,
        Stage.SYNC_FILE_MOUNTS, Stage.SETUP, Stage.EXEC,
    ]
    with locks.cluster_lock(cluster_name):
        info = _existing_cluster_info(cluster_name, task.resources)
        if info is not None:
            logger.info('Reusing cluster %s', cluster_name)
        else:
            if Stage.PROVISION not in run_stages:
                raise exceptions.ClusterDoesNotExist(cluster_name)
            if Stage.OPTIMIZE in run_stages:
                optimizer_lib.optimize(task, target=optimize_target,
                                       quiet=quiet)
            # Best-first candidate list feeds the failover loop (reference:
            # the optimizer's output seeds RetryingVmProvisioner's zones).
            candidates = _failover_candidates(task, optimize_target)
            # Two relaxation tiers (serve/spot_placer.py): HARD blocks
            # (preemption cooldowns) are only relaxed when they exclude
            # EVERY candidate — capacity moved on — while SOFT avoids
            # (zone spreading) are dropped against the already-filtered
            # list, so spreading pressure can never push a launch back
            # into a zone that just preempted.
            if blocked_placements:
                blocked_set = set(blocked_placements)
                keep = [c for c in candidates
                        if (c.region, c.zone) not in blocked_set]
                candidates = keep or candidates
            if avoid_placements:
                avoid_set = set(avoid_placements)
                keep = [c for c in candidates
                        if (c.region, c.zone) not in avoid_set]
                candidates = keep or candidates
            with trace.span('launch.provision', cluster=cluster_name):
                info = backend.provision(task, cluster_name, candidates)

        if Stage.SYNC_WORKDIR in run_stages and task.workdir:
            with trace.span('launch.sync_workdir'):
                backend.sync_workdir(info, task.workdir)
        if Stage.SYNC_FILE_MOUNTS in run_stages and (task.file_mounts or
                                                     task.storage_mounts):
            mounts = dict(task.file_mounts)
            for mp, spec in task.storage_mounts.items():
                mounts[mp] = spec['source']
            with trace.span('launch.sync_file_mounts'):
                backend.sync_file_mounts(info, mounts)
        if Stage.SYNC_FILE_MOUNTS in run_stages and task.volumes:
            with trace.span('launch.mount_volumes'):
                backend.mount_volumes(info, task)
        if Stage.SETUP in run_stages:
            with trace.span('launch.setup'):
                backend.setup(info, task)
        job_id = -1
        if Stage.EXEC in run_stages and task.run:
            with trace.span('launch.exec', cluster=cluster_name):
                job_id = backend.execute(info, task, detach=detach_run)
        # Apply requested autostop.
        auto = task.resources.autostop
        if auto is not None and auto.enabled and hasattr(backend,
                                                         'set_autostop'):
            backend.set_autostop(info, auto.idle_minutes, auto.down)
    return job_id, info


def _failover_candidates(
        task: task_lib.Task,
        target: optimizer_lib.OptimizeTarget) -> List[catalog.Candidate]:
    """Best-first candidate list for the failover loop.

    When the optimizer already placed the task (``best_resources``), its
    placement leads the list so the chosen cloud/region is honored —
    critical for job-group gang placement, where every member's
    best_resources share one region. Remaining candidates stay as
    failover fallbacks (availability still wins over preference,
    mirroring the reference's optimizer-seeds-failover design).
    """
    plans = optimizer_lib._fill_candidates(task, target)  # noqa: SLF001
    seen = set()
    out = []
    for p in plans:
        key = (p.candidate.cloud, p.candidate.region, p.candidate.zone,
               p.candidate.instance_type)
        if key in seen:
            continue
        seen.add(key)
        out.append(p.candidate)
    br = task.best_resources
    if br is not None:
        def _preferred(c: catalog.Candidate) -> int:
            return 0 if (c.cloud == br.cloud and
                         (br.region is None or c.region == br.region) and
                         (br.zone is None or c.zone == br.zone)) else 1
        out.sort(key=_preferred)   # stable: best-first within groups
    return out


@usage.entrypoint(name='launch_dag')
@timeline.event(name='execution.launch_dag')
@trace.traced(name='execution.launch_dag')
def launch_dag(
    dag,
    *,
    backend: Optional[backend_lib.Backend] = None,
    optimize_target: optimizer_lib.OptimizeTarget =
        optimizer_lib.OptimizeTarget.COST,
    detach_run: bool = True,
    quiet: bool = True,
    down: bool = False,
) -> List[Tuple[str, int, ClusterInfo]]:
    """Execute a multi-task Dag (reference ``_execute_dag``,
    sky/execution.py:293).

    Chains run serially in topological order, each task on its own
    cluster (optionally downed after, like the reference's pipeline
    semantics); ``detach_run`` is ignored for chains since stage N+1
    must wait on stage N anyway. Job groups (``execution: parallel``)
    are optimized with the same-infra gang constraint and launched
    concurrently; with ``down=True`` each member autodowns (autostop
    idle=0, down) once its job queue drains, so the call can still
    return without blocking on job completion.

    Returns a list of (cluster_name, job_id, info) per task, in
    execution order.
    """
    from skypilot_tpu import dag as dag_lib  # local: avoid import cycle

    assert isinstance(dag, dag_lib.Dag), dag
    backend = backend or backend_lib.TpuVmBackend()
    results: List[Tuple[str, int, ClusterInfo]] = []
    if dag.is_job_group():
        optimizer_lib.Optimizer.optimize_job_group(dag, optimize_target,
                                                   quiet=quiet)
        import concurrent.futures as cf

        from skypilot_tpu.jobs import job_group_networking as jg_net
        group_name = dag.name or 'jobgroup'
        # Two-phase launch: every member's slice must EXIST before any
        # member runs, or the peers' addresses can't be known. Phase 1
        # provisions the whole gang concurrently; phase 2 injects the
        # peer map (env + best-effort hosts file) and runs
        # setup/exec with it.
        with cf.ThreadPoolExecutor(max_workers=len(dag.tasks)) as pool:
            futs = [
                pool.submit(launch, t, None, backend=backend,
                            # placement fixed by the gang optimizer above
                            stages=[Stage.PROVISION],
                            detach_run=detach_run, quiet=quiet)
                for t in dag.tasks
            ]
            infos = [f.result()[1] for f in futs]
        infos_by_task = {
            (t.name or f'task{i}'): info
            for i, (t, info) in enumerate(zip(dag.tasks, infos))}
        genv = jg_net.group_env(group_name, infos_by_task)
        jg_net.inject_hosts(backend, group_name, infos_by_task)
        with cf.ThreadPoolExecutor(max_workers=len(dag.tasks)) as pool:
            futs = []
            for i, t in enumerate(dag.tasks):
                t.envs.update(genv)
                futs.append(pool.submit(
                    launch, t, infos[i].cluster_name, backend=backend,
                    stages=[Stage.SYNC_WORKDIR, Stage.SYNC_FILE_MOUNTS,
                            Stage.SETUP, Stage.EXEC],
                    detach_run=detach_run, quiet=quiet))
            for t, f in zip(dag.tasks, futs):
                job_id, info = f.result()
                results.append((info.cluster_name, job_id, info))
        if down:
            for _, _, info in results:
                backend.set_autostop(info, 0, True)
        return results
    # Serial chain: run to completion before the next stage starts.
    for t in dag.topological_order():
        job_id, info = launch(t, None, backend=backend,
                              optimize_target=optimize_target,
                              detach_run=False, quiet=quiet)
        results.append((info.cluster_name, job_id, info))
        if job_id >= 0:
            status = backend.wait_job(info, job_id)
            if status != common.JobStatus.SUCCEEDED:
                raise exceptions.CommandError(
                    1, f'dag stage {t.name or "<task>"}',
                    f'stage failed with status {status.value}; aborting '
                    f'downstream tasks.')
        if down:
            backend.teardown(info, terminate=True)
    return results


@usage.entrypoint(name='exec')
@timeline.event(name='execution.exec')
@trace.traced(name='execution.exec')
def exec(  # noqa: A001 — mirrors the reference's public name
    task: task_lib.Task,
    cluster_name: str,
    *,
    backend: Optional[backend_lib.Backend] = None,
    detach_run: bool = True,
    caller: Optional[Dict[str, Any]] = None,
    include_setup: bool = False,
) -> Tuple[int, ClusterInfo]:
    """Run a task on an existing cluster, skipping provision/setup
    (reference sky/execution.py:825). ``include_setup`` opts the task's
    setup back in as the job's setup phase — pool jobs need it, since
    their worker never ran this task's SETUP stage."""
    # Private-workspace gate: running commands on a cluster is entering
    # the workspace the cluster was LAUNCHED in (its record carries it) —
    # not whatever workspace happens to be active in this process.
    from skypilot_tpu import users as users_lib
    from skypilot_tpu import workspaces as workspaces_lib
    record_ws = state.get_cluster(cluster_name)
    workspaces_lib.check_workspace_permission(
        caller if caller is not None else users_lib.core.ensure_user(),
        (record_ws.get('workspace') if record_ws else None) or
        workspaces_lib.active_workspace())
    backend = backend or backend_lib.TpuVmBackend()
    with locks.cluster_lock(cluster_name):
        record = state.get_cluster(cluster_name)
        if record is None:
            raise exceptions.ClusterDoesNotExist(cluster_name)
        info = _existing_cluster_info(cluster_name, task.resources)
        assert info is not None
        if task.workdir:
            backend.sync_workdir(info, task.workdir)
        job_id = backend.execute(info, task, detach=detach_run,
                                 include_setup=include_setup)
    return job_id, info
