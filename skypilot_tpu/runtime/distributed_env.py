"""`jax.distributed` / libtpu environment wiring for slice hosts.

This replaces the reference's rank/world env contract
(``SKYPILOT_NODE_RANK``/``SKYPILOT_NODE_IPS``/``SKYPILOT_NUM_NODES``,
reference sky/skylet/constants.py:469-474, consumed by torchrun in
examples/resnet_distributed_torch.yaml:31-34). The TPU equivalent wires the
XLA/libtpu process group instead of NCCL:

- ``JAX_COORDINATOR_ADDRESS`` / ``JAX_NUM_PROCESSES`` / ``JAX_PROCESS_ID``:
  consumed by ``jax.distributed.initialize()`` with no arguments.
- ``TPU_WORKER_ID`` / ``TPU_WORKER_HOSTNAMES``: libtpu's own multi-host
  wiring (what the TPU VM metadata server would provide); exporting them
  makes the framework authoritative, which is required when running
  non-default topologies or fake local slices.
- ``MEGASCALE_*``: multislice (DCN-connected slices) coordinator variables,
  emitted only when a job spans multiple slices.

The generic ``SKY_TPU_*`` variables remain for user scripts that want
rank/ips without importing jax.
"""
from __future__ import annotations

from typing import Dict, List, Optional

from skypilot_tpu import topology

# Port the jax.distributed coordinator (host 0) listens on.
COORDINATOR_PORT = 8476
MEGASCALE_PORT = 8081

# Generic env (cloud-framework-agnostic), reference constants.py:469-474.
NODE_RANK_ENV = 'SKY_TPU_NODE_RANK'
NODE_IPS_ENV = 'SKY_TPU_NODE_IPS'
NUM_NODES_ENV = 'SKY_TPU_NUM_NODES'
NUM_CHIPS_PER_NODE_ENV = 'SKY_TPU_NUM_CHIPS_PER_NODE'


def make_env(host_ips: List[str],
             rank: int,
             tpu_slice: Optional[topology.TpuSlice],
             *,
             num_slices: int = 1,
             slice_id: int = 0,
             megascale_coordinator: Optional[str] = None,
             coordinator_ip: Optional[str] = None) -> Dict[str, str]:
    """Env vars for the process running on host `rank` of a slice.

    `host_ips` is THIS slice's host list and `rank` the host index within
    it (libtpu's TPU_WORKER_* wiring is per-slice). For multislice jobs
    (num_slices > 1) `slice_id` identifies the slice, `coordinator_ip`
    must be host 0 of slice 0 (the ONE jax.distributed coordinator for the
    global process group), and MEGASCALE vars carry the DCN-level wiring.
    """
    num_hosts = len(host_ips)
    coordinator = f'{coordinator_ip or host_ips[0]}:{COORDINATOR_PORT}'
    env = {
        NODE_RANK_ENV: str(rank),
        NODE_IPS_ENV: '\n'.join(host_ips),
        NUM_NODES_ENV: str(num_hosts),
        # jax.distributed.initialize() picks these up directly.
        'JAX_COORDINATOR_ADDRESS': coordinator,
        'JAX_NUM_PROCESSES': str(num_hosts * num_slices),
        'JAX_PROCESS_ID': str(slice_id * num_hosts + rank),
    }
    if tpu_slice is not None:
        env[NUM_CHIPS_PER_NODE_ENV] = str(tpu_slice.chips_per_host)
        # libtpu multi-host wiring (authoritative topology).
        env['TPU_WORKER_ID'] = str(rank)
        env['TPU_WORKER_HOSTNAMES'] = ','.join(host_ips)
        env['TPU_CHIPS_PER_HOST_BOUNDS'] = _chips_per_host_bounds(tpu_slice)
        env['TPU_HOST_BOUNDS'] = ','.join(
            str(b) for b in tpu_slice.host_bounds())
        env['TPU_ACCELERATOR_TYPE'] = tpu_slice.accelerator_type
    if num_slices > 1:
        assert megascale_coordinator is not None
        env.update({
            'MEGASCALE_COORDINATOR_ADDRESS':
                f'{megascale_coordinator}:{MEGASCALE_PORT}',
            'MEGASCALE_NUM_SLICES': str(num_slices),
            'MEGASCALE_SLICE_ID': str(slice_id),
        })
    return env


def _chips_per_host_bounds(s: topology.TpuSlice) -> str:
    """The per-host chip block as 'x,y,z' (complement of host_bounds)."""
    hb = s.host_bounds()
    dims = [t // b for t, b in zip(s.ici_topology, hb)]
    while len(dims) < 3:
        dims.append(1)
    return ','.join(str(d) for d in dims)
