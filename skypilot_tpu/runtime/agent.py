"""On-host agent daemon — the skylet equivalent.

Counterpart of the reference's ``sky/skylet/skylet.py`` (gRPC server for
autostop/jobs services + periodic event loop, :45-85). Differences:

- HTTP/JSON over aiohttp instead of gRPC+protobuf (fastapi/protoc stubs are
  not part of this environment; the wire format is a private detail behind
  ``AgentClient``).
- **No Ray.** Gang execution is native: the agent knows its slice's host
  list and fans a job out to every host simultaneously with
  `jax.distributed` env injected per rank
  (``runtime/distributed_env.py``) — replacing the reference's generated
  Ray placement-group driver program (reference
  sky/backends/task_codegen.py:439-465,559).

Modes:
- ``local-slice``: one agent simulates all N hosts of a fake slice by
  spawning N local subprocesses per job (the test/E2E backend).
- ``host``: one agent per real TPU host; the head host's agent fans out to
  peer agents' /run_rank endpoint over the slice's internal network.

Run: ``python -m skypilot_tpu.runtime.agent --cluster-dir DIR``
(config read from DIR/agent_config.json; chosen port written to
DIR/agent.json).
"""
from __future__ import annotations

import argparse
import asyncio
import hmac
import json
import os
import signal
import sys
import time
from typing import Any, Dict, List, Optional

from aiohttp import web

from skypilot_tpu import topology
from skypilot_tpu.observability import trace as trace_lib
from skypilot_tpu.runtime import distributed_env
from skypilot_tpu.runtime import job_lib
from skypilot_tpu.utils import common
from skypilot_tpu.utils import failpoints

POLL_INTERVAL = 1.0
AUTOSTOP_CHECK_INTERVAL = 5.0


class Agent:
    def __init__(self, cluster_dir: str):
        # A provision-time trace context inherited from the spawning
        # provisioner must not become the parent of every span this
        # long-lived daemon ever records — context arrives per request
        # (traceparent header) or per job (SKY_TPU_TRACEPARENT in the
        # job's envs), never from the daemon's own environment.
        os.environ.pop(trace_lib.CTX_ENV_VAR, None)
        trace_lib.set_hop('agent')
        self.cluster_dir = os.path.abspath(cluster_dir)
        with open(os.path.join(self.cluster_dir, 'agent_config.json'),
                  encoding='utf-8') as f:
            self.config: Dict[str, Any] = json.load(f)
        # Tracing config rides agent_config.json for real (remote)
        # hosts, where the provisioner's environment does not reach:
        # `trace_enabled` turns span recording on, `trace_collector`
        # names the URL spans ship to (the API server as seen FROM the
        # cluster). On the local fake slice the inherited env already
        # carries both.
        if self.config.get('trace_enabled'):
            os.environ.setdefault(trace_lib.ENV_VAR, '1')
        if self.config.get('trace_collector'):
            os.environ.setdefault(trace_lib.COLLECTOR_ENV_VAR,
                                  str(self.config['trace_collector']))
        self.mode: str = self.config.get('mode', 'local-slice')
        self.host_rank: int = int(self.config.get('host_rank', 0))
        self.host_ips: List[str] = self.config.get('host_ips', ['127.0.0.1'])
        self.peer_agent_urls: List[str] = self.config.get(
            'peer_agent_urls', [])
        slice_name = self.config.get('tpu_slice')
        self.tpu_slice: Optional[topology.TpuSlice] = (
            topology.parse_tpu(slice_name) if slice_name else None)
        self.num_hosts: int = int(self.config.get(
            'num_hosts', self.tpu_slice.num_hosts if self.tpu_slice else 1))
        # Multislice (DCN): num_hosts is per slice; this host's slice is
        # config['slice_id'] (host mode); local-slice mode simulates all
        # num_slices * num_hosts ranks in one process tree.
        self.num_slices: int = int(self.config.get('num_slices', 1))
        self.slice_id: int = int(self.config.get('slice_id', 0))
        self.jobs = job_lib.JobTable(
            os.path.join(self.cluster_dir, 'jobs.db'))
        self.started_at = time.time()
        # Per-cluster shared secret, provision-time generated. The agent
        # binds a routable interface on real clouds, so every endpoint
        # except /health requires it (the reference never exposes skylet
        # at all — gRPC rides an SSH tunnel,
        # cloud_vm_ray_backend.py:2288-2320; a bearer token over the VPC
        # is this framework's equivalent trust boundary).
        self._token_cache = (-1.0, self.config.get('auth_token'))
        # Cluster TLS (utils/tls.py): cert+key PEMs ride agent_config
        # next to the bearer token; all agents of a cluster share one
        # cert, so peer fan-out pins the same fingerprint it serves.
        self.tls_cert_pem: Optional[str] = self.config.get('tls_cert_pem')
        self.tls_key_pem: Optional[str] = self.config.get('tls_key_pem')
        self.cert_fingerprint: Optional[str] = None
        if self.tls_cert_pem:
            from skypilot_tpu.utils import tls
            self.cert_fingerprint = tls.fingerprint_of_pem(
                self.tls_cert_pem)
        # autostop state (reference sky/skylet/autostop_lib.py)
        self._autostop_file = os.path.join(self.cluster_dir, 'autostop.json')
        # job_id -> list of subprocess handles (local-slice mode)
        self._procs: Dict[int, List[asyncio.subprocess.Process]] = {}
        # /exec invocations get unique negative ids so their proc/pgid
        # bookkeeping is cleaned per call (a shared -1 key would
        # accumulate handles forever on exec-heavy clusters).
        self._exec_counter = 0
        self._cancelled: set = set()
        # submit_id -> job_id dedup map for idempotent /submit retries
        # (insertion-ordered; oldest entries evicted past the cap).
        self._submit_ids: Dict[str, int] = {}
        # Restart reconciliation: a previous agent killed mid-job (stop,
        # OOM, crash) leaves INIT/SETTING_UP/RUNNING rows behind with no
        # process behind them. The FIFO scheduler gates on
        # running_jobs(), so an unreconciled row would wedge the queue
        # FOREVER (every later submit stays PENDING). This process just
        # started: no job of ours can be running yet — mark the
        # orphans FAILED (the managed-jobs controller treats a terminal
        # status on a healthy slice per its restart policy; a preempted
        # slice never restarts an agent, so the preemption-detection
        # path in _kill_agent is unaffected).
        for stale in self.jobs.running_jobs():
            self.jobs.set_status(stale['job_id'], job_lib.JobStatus.FAILED)
        # Native orphan reaper (native/reaper.cc): if this agent is
        # SIGKILLed mid-job, the rank process groups recorded in the
        # pgid file are torn down so no leaked rank wedges the TPU chip
        # (reference subprocess_daemon.py:184, rebuilt native).
        self._pgid_file = os.path.join(self.cluster_dir, 'job_pgids')
        open(self._pgid_file, 'w', encoding='utf-8').close()
        self._start_reaper()

    def _auth_token(self) -> Optional[str]:
        """Live cluster token: re-read agent_config.json when it changes
        so a re-provision can rotate the secret without an agent
        restart (providers rewrite the config on every run_instances)."""
        path = os.path.join(self.cluster_dir, 'agent_config.json')
        try:
            mtime = os.stat(path).st_mtime
        except OSError:
            return self._token_cache[1]
        if mtime != self._token_cache[0]:
            try:
                with open(path, encoding='utf-8') as f:
                    tok = json.load(f).get('auth_token')
                self._token_cache = (mtime, tok)
            except (OSError, json.JSONDecodeError):
                pass   # mid-rewrite read; keep the cached token
        return self._token_cache[1]

    def _auth_headers(self) -> Dict[str, str]:
        tok = self._auth_token()
        return {'Authorization': f'Bearer {tok}'} if tok else {}

    def _start_reaper(self) -> None:
        import subprocess as sp

        from skypilot_tpu.runtime import native_build
        reaper = native_build.ensure_reaper()
        if reaper is None:
            return
        sp.Popen([reaper, '--parent-pid', str(os.getpid()),
                  '--pgid-file', self._pgid_file],
                 stdout=sp.DEVNULL, stderr=sp.DEVNULL,
                 start_new_session=True)

    def _record_pgid(self, pid: int) -> None:
        try:
            with open(self._pgid_file, 'a', encoding='utf-8') as f:
                f.write(f'{pid}\n')
        except OSError:
            pass

    def _prune_pgids(self, pids) -> None:
        """Drop finished ranks' pgids from the reaper file — but ONLY
        groups that are really gone: a rank leader can exit while a
        backgrounded child keeps the group alive, and that survivor
        must stay covered by the reaper/teardown (it could be holding
        libtpu). Entries only ever accumulated before, which was the
        opposite hazard: teardown acting on pids the OS had recycled."""
        gone = set()
        for p in pids:
            try:
                os.killpg(int(p), 0)
            except ProcessLookupError:
                gone.add(str(p))
            except PermissionError:
                pass   # group alive (not ours to probe): keep covered
        if not gone:
            return
        try:
            with open(self._pgid_file, encoding='utf-8') as f:
                live = [ln for ln in f.read().split()
                        if ln and ln not in gone]
            tmp = self._pgid_file + '.tmp'
            with open(tmp, 'w', encoding='utf-8') as f:
                f.write(''.join(f'{ln}\n' for ln in live))
            os.replace(tmp, self._pgid_file)
        except OSError:
            pass

    # ---------------- job execution --------------------------------------
    def _rank_env(self, rank: int, job_envs: Dict[str, str],
                  job_id: int) -> Dict[str, str]:
        """Env for global host index `rank` (slice-aware).

        `rank` spans all slices; slice j owns ranks
        [j*num_hosts, (j+1)*num_hosts). make_env gets the slice-local view
        (libtpu TPU_WORKER_* is per slice) plus the global coordinator.
        """
        env = dict(os.environ)
        sid, in_rank = divmod(rank, self.num_hosts)
        slice_ips = self.host_ips[sid * self.num_hosts:
                                  (sid + 1) * self.num_hosts]
        env.update(distributed_env.make_env(
            slice_ips, in_rank, self.tpu_slice,
            num_slices=self.num_slices, slice_id=sid,
            megascale_coordinator=(self.host_ips[0]
                                   if self.num_slices > 1 else None),
            coordinator_ip=self.host_ips[0]))
        env.update(job_envs)
        env['SKY_TPU_JOB_ID'] = str(job_id)
        if self.mode == 'local-slice':
            # Fake-slice sandbox root: absolute file-mount destinations land
            # under this dir (a real host would use / directly).
            env['SKY_TPU_HOST_ROOT'] = os.path.join(self.cluster_dir,
                                                    f'host{rank}')
            # Rank cwd is the host workdir, so first-party modules (e.g.
            # `python -m skypilot_tpu.infer.server` replicas) are only
            # importable if the framework root rides PYTHONPATH — the
            # local analog of the wheel a real host has installed.
            pkg_root = os.path.dirname(os.path.dirname(
                os.path.dirname(os.path.abspath(__file__))))
            prior_pp = env.get('PYTHONPATH', '')
            if pkg_root not in prior_pp.split(os.pathsep):
                env['PYTHONPATH'] = (f'{pkg_root}{os.pathsep}{prior_pp}'
                                     if prior_pp else pkg_root)
            # Fake slices must not grab a real TPU. Overridden (not
            # setdefault): the inherited environment may pin a TPU platform,
            # and both selection variables must agree for every jax version.
            env['JAX_PLATFORMS'] = 'cpu'
            env['JAX_PLATFORM_NAME'] = 'cpu'
            if self.tpu_slice is not None:
                flag = ('--xla_force_host_platform_device_count='
                        f'{self.tpu_slice.chips_per_host}')
                prior = env.get('XLA_FLAGS', '')
                if '--xla_force_host_platform_device_count' not in prior:
                    env['XLA_FLAGS'] = f'{prior} {flag}'.strip()
        return env

    def _rank_cwd(self, rank: int) -> str:
        if self.mode == 'local-slice':
            d = os.path.join(self.cluster_dir, f'host{rank}', 'workdir')
        else:
            d = os.path.join(self.cluster_dir, 'workdir')
        os.makedirs(d, exist_ok=True)
        return d

    async def _run_rank(self, job_id: int, rank: int, cmd: str,
                        envs: Dict[str, str], log_path: str) -> int:
        os.makedirs(os.path.dirname(log_path), exist_ok=True)
        with open(log_path, 'ab') as logf:
            proc = await asyncio.create_subprocess_shell(
                cmd,
                cwd=self._rank_cwd(rank),
                env=self._rank_env(rank, envs, job_id),
                stdout=logf,
                stderr=asyncio.subprocess.STDOUT,
                start_new_session=True,
            )
        self._procs.setdefault(job_id, []).append(proc)
        # start_new_session=True → the child's pgid is its pid.
        self._record_pgid(proc.pid)
        return await proc.wait()

    async def _run_job(self, job: Dict[str, Any]) -> None:
        job_id = job['job_id']
        log_dir = job['log_dir']
        os.makedirs(log_dir, exist_ok=True)
        # Re-adopt the submitting request's trace context (persisted in
        # the job envs by h_submit) — the job-runtime hop of the trace.
        trace_ctx = trace_lib.context_from(
            (job['envs'] or {}).get(trace_lib.CTX_ENV_VAR))
        try:
            with trace_ctx:
                if job['setup_cmd']:
                    self.jobs.set_status(job_id,
                                         job_lib.JobStatus.SETTING_UP)
                    with trace_lib.span('job.setup', job_id=job_id):
                        rcs = await self._fan_out(job_id,
                                                  job['setup_cmd'],
                                                  job['envs'], log_dir,
                                                  'setup')
                    if any(rc != 0 for rc in rcs):
                        self.jobs.set_status(
                            job_id, job_lib.JobStatus.FAILED_SETUP)
                        return
                self.jobs.set_status(job_id, job_lib.JobStatus.RUNNING)
                with trace_lib.span('job.run', job_id=job_id,
                                    hosts=self.num_hosts *
                                    self.num_slices) as jspan:
                    rcs = await self._fan_out(job_id, job['run_cmd'],
                                              job['envs'], log_dir, 'run')
                    if jspan is not None:
                        jspan.set_attr('returncodes', rcs)
            if job_id in self._cancelled:
                self.jobs.set_status(job_id, job_lib.JobStatus.CANCELLED)
            elif all(rc == 0 for rc in rcs):
                self.jobs.set_status(job_id, job_lib.JobStatus.SUCCEEDED)
            else:
                self.jobs.set_status(job_id, job_lib.JobStatus.FAILED)
        except Exception as e:  # noqa: BLE001 — agent must not die on a job
            with open(os.path.join(log_dir, 'agent_error.log'), 'a',
                      encoding='utf-8') as f:
                f.write(f'{e!r}\n')
            self.jobs.set_status(job_id, job_lib.JobStatus.FAILED)
        finally:
            procs = self._procs.pop(job_id, None) or []
            self._prune_pgids(p.pid for p in procs)
            if trace_lib.enabled():
                await asyncio.get_event_loop().run_in_executor(
                    None, trace_lib.flush)

    async def _fan_out(self, job_id: int, cmd: str, envs: Dict[str, str],
                       log_dir: str, phase: str) -> List[int]:
        """Run `cmd` on every host of the slice simultaneously."""
        if self.mode == 'local-slice':
            tasks = [
                self._run_rank(job_id, r, cmd, envs,
                               os.path.join(log_dir, f'rank{r}_{phase}.log'))
                for r in range(self.num_hosts * self.num_slices)
            ]
            return list(await asyncio.gather(*tasks))
        # host mode: this agent runs its own rank; peers run theirs.
        import aiohttp
        my = self._run_rank(job_id, self.host_rank, cmd, envs,
                            os.path.join(log_dir,
                                         f'rank{self.host_rank}_{phase}.log'))

        from skypilot_tpu.utils import tls
        peer_ssl = tls.aiohttp_ssl(self.cert_fingerprint)

        async def call_peer(sess: 'aiohttp.ClientSession', url: str) -> int:
            # Response body must be read while the session is open. The
            # cluster token rides the fan-out too — peers enforce it.
            async with sess.post(f'{url}/run_rank', json={
                    'job_id': job_id, 'cmd': cmd, 'envs': envs,
                    'phase': phase,
            }, headers=self._auth_headers(), ssl=peer_ssl,
                    timeout=aiohttp.ClientTimeout(total=None)) as res:
                body = await res.json()
                return int(body.get('returncode', 255))

        async with aiohttp.ClientSession() as sess:
            results = await asyncio.gather(
                my, *(call_peer(sess, url) for url in self.peer_agent_urls),
                return_exceptions=True)
        return [255 if isinstance(r, BaseException) else int(r)
                for r in results]

    async def scheduler_loop(self) -> None:
        """FIFO, one job at a time (reference JobSchedulerEvent,
        sky/skylet/events.py:69)."""
        while True:
            try:
                if not self.jobs.running_jobs():
                    nxt = self.jobs.next_pending()
                    if nxt is not None:
                        self.jobs.set_status(nxt['job_id'],
                                             job_lib.JobStatus.INIT)
                        asyncio.get_event_loop().create_task(
                            self._run_job(nxt))
            except Exception:  # noqa: BLE001
                pass
            await asyncio.sleep(POLL_INTERVAL)

    # ---------------- autostop -------------------------------------------
    def _autostop_config(self) -> Dict[str, Any]:
        if os.path.exists(self._autostop_file):
            with open(self._autostop_file, encoding='utf-8') as f:
                return json.load(f)
        return {'idle_minutes': -1, 'down': False}

    async def heartbeat_loop(self) -> None:
        """Reference UsageHeartbeatReportEvent (sky/skylet/events.py:153):
        the on-cluster runtime reports liveness into the usage stream."""
        from skypilot_tpu import usage
        while True:
            try:
                usage.record('agent-heartbeat', 0.0, 'ok', {
                    'cluster': self.config.get('cluster_name', '?'),
                    'mode': self.mode,
                    'num_hosts': self.num_hosts,
                    'num_slices': self.num_slices,
                    'idle': self.jobs.is_idle(),
                })
            except Exception:  # noqa: BLE001 — telemetry is best-effort
                pass
            await asyncio.sleep(600.0)

    # ---------------- log GC ----------------------------------------------
    def _gc_logs(self, now: Optional[float] = None) -> None:
        """Prune finished jobs' logs by age AND total size (reference
        sky/jobs/log_gc.py: 7-day retention, hourly loop; the size
        budget is the TPU-host twist — a long-lived slice writes
        per-rank logs forever and eventually fills the host disk).

        Never touches a non-terminal job's logs; exec logs (setup /
        pre-exec stages) age out the same way. Tunables ride
        agent_config.json: log_retention_hours (negative disables),
        log_budget_mb (total across finished-job + exec logs).
        """
        import shutil
        now = now if now is not None else time.time()
        retention_h = float(self.config.get('log_retention_hours', 168))
        budget_bytes = float(self.config.get('log_budget_mb',
                                             1024)) * 1e6
        if retention_h < 0:
            return
        job_root = os.path.join(self.cluster_dir, 'job_logs')
        exec_root = os.path.join(self.cluster_dir, 'exec_logs')
        # Candidate dirs: terminal jobs' log dirs + all exec log dirs.
        candidates = []   # (mtime, size, path)
        terminal_ids = {
            str(j['job_id']) for j in self.jobs.list_jobs()
            if j['status'].is_terminal()}
        known_ids = {str(j['job_id']) for j in self.jobs.list_jobs()}
        if os.path.isdir(job_root):
            for name in os.listdir(job_root):
                # Unknown dirs (job row gone) are prunable; live jobs
                # are not.
                if name in known_ids and name not in terminal_ids:
                    continue
                candidates.append(os.path.join(job_root, name))
        if os.path.isdir(exec_root):
            candidates.extend(os.path.join(exec_root, name)
                              for name in os.listdir(exec_root))
        entries = []
        for path in candidates:
            try:
                mtime = os.path.getmtime(path)
                size = sum(
                    os.path.getsize(os.path.join(r, f))
                    for r, _, fs in os.walk(path) for f in fs)
            except OSError:
                continue
            entries.append((mtime, size, path))
        # Age pass.
        kept = []
        for mtime, size, path in sorted(entries):
            if now - mtime > retention_h * 3600:
                shutil.rmtree(path, ignore_errors=True)
            else:
                kept.append((mtime, size, path))
        # Size pass: oldest finished logs go first until under budget.
        total = sum(size for _, size, _ in kept)
        for mtime, size, path in kept:
            if total <= budget_bytes:
                break
            shutil.rmtree(path, ignore_errors=True)
            total -= size

    async def log_gc_loop(self) -> None:
        """Hourly (clamped like the reference's _next_gc_interval)."""
        retention_h = float(self.config.get('log_retention_hours', 168))
        interval = max(min(retention_h * 3600, 3600.0), 30.0)
        while True:
            try:
                self._gc_logs()
            except Exception:  # noqa: BLE001 — GC must not kill agent
                pass
            await asyncio.sleep(interval)

    async def autostop_loop(self) -> None:
        """Reference AutostopEvent (sky/skylet/events.py:161): the cluster
        tears *itself* down after idling."""
        while True:
            await asyncio.sleep(AUTOSTOP_CHECK_INTERVAL)
            try:
                cfg = self._autostop_config()
                idle_min = cfg.get('idle_minutes', -1)
                if idle_min is None or idle_min < 0:
                    continue
                if not self.jobs.is_idle():
                    continue
                anchor = max(self.jobs.last_activity(), self.started_at,
                             cfg.get('set_at', 0.0))
                if time.time() - anchor >= idle_min * 60:
                    self._trigger_autostop(bool(cfg.get('down', False)))
            except Exception:  # noqa: BLE001
                pass

    def _trigger_autostop(self, down: bool) -> None:
        marker = {
            'triggered_at': time.time(),
            'action': 'down' if down else 'stop',
        }
        with open(os.path.join(self.cluster_dir, 'autostop_triggered.json'),
                  'w', encoding='utf-8') as f:
            json.dump(marker, f)
        if self.mode == 'host':
            # Real cloud: the agent deletes/stops its own slice via the
            # provider API (reference autostop_lib self-teardown).
            try:
                from skypilot_tpu.provision.gcp import instance as gcp
                pc = self.config.get('provider_config', {})
                if down:
                    gcp.terminate_instances(self.config['cluster_name'], pc)
                else:
                    gcp.stop_instances(self.config['cluster_name'], pc)
            except Exception:  # noqa: BLE001
                pass
        else:
            # Local fake slice: mark hosts stopped; the engine's status
            # refresh reconciles.
            for r in range(self.num_hosts * self.num_slices):
                hd = os.path.join(self.cluster_dir, f'host{r}')
                if os.path.isdir(hd):
                    with open(os.path.join(hd, 'state'), 'w',
                              encoding='utf-8') as f:
                        f.write('STOPPED' if not down else 'TERMINATED')

    # ---------------- HTTP handlers --------------------------------------
    async def h_health(self, _req: web.Request) -> web.Response:
        # FailpointError surfaces as aiohttp's 500 — from the client's
        # side, indistinguishable from a crashing agent (the point).
        await failpoints.hit_async('agent.health')
        return web.json_response({
            'status': 'healthy',
            'uptime_s': time.time() - self.started_at,
            'idle': self.jobs.is_idle(),
            'mode': self.mode,
            'num_hosts': self.num_hosts,
            'num_slices': self.num_slices,
        })

    async def h_submit(self, req: web.Request) -> web.Response:
        # BEFORE any state change: an injected submit failure must be
        # safely retryable (no half-created job row to double-run).
        await failpoints.hit_async('agent.submit')
        body = await req.json()
        # Idempotent retry: the client stamps each LOGICAL submit with a
        # fresh submit_id and reuses it across retries. If the previous
        # attempt's response was lost AFTER the job row committed, the
        # retry must return the same job instead of double-running the
        # workload. In-memory is enough: the dedup window is the
        # client's retry loop, and an agent restart within it also loses
        # the job row the duplicate would have shadowed.
        submit_id = body.get('submit_id')
        if submit_id:
            prior = self._submit_ids.get(str(submit_id))
            if prior is not None:
                return web.json_response({'job_id': prior})
        log_dir = os.path.join(self.cluster_dir, 'job_logs')
        envs = dict(body.get('envs', {}))
        # Job execution is async (the scheduler loop picks it up later):
        # persist the submit's trace context in the job's envs so the
        # runtime spans (job.setup/job.run) — and the rank processes,
        # which inherit the env — parent to this submission.
        trace_lib.child_env(envs)
        job_id = self.jobs.add_job(
            name=body.get('name', 'job'),
            run_cmd=body['run'],
            setup_cmd=body.get('setup'),
            envs=envs,
            num_hosts=self.num_hosts * self.num_slices,
            log_dir='')
        log_dir = os.path.join(log_dir, str(job_id))
        self.jobs._conn.execute(  # set final log dir now that id is known
            'UPDATE jobs SET log_dir=? WHERE job_id=?', (log_dir, job_id))
        self.jobs._conn.commit()
        if submit_id:
            self._submit_ids[str(submit_id)] = job_id
            if len(self._submit_ids) > 4096:   # bound the dedup window
                self._submit_ids.pop(next(iter(self._submit_ids)))
        return web.json_response({'job_id': job_id})

    async def h_jobs(self, _req: web.Request) -> web.Response:
        out = []
        for j in self.jobs.list_jobs():
            j = dict(j)
            j['status'] = j['status'].value
            out.append(j)
        return web.json_response({'jobs': out})

    async def h_job(self, req: web.Request) -> web.Response:
        job = self.jobs.get(int(req.match_info['job_id']))
        if job is None:
            return web.json_response({'error': 'not found'}, status=404)
        job = dict(job)
        job['status'] = job['status'].value
        return web.json_response(job)

    async def h_cancel(self, req: web.Request) -> web.Response:
        job_id = int(req.match_info['job_id'])
        job = self.jobs.get(job_id)
        if job is None:
            return web.json_response({'error': 'not found'}, status=404)
        self._cancelled.add(job_id)
        for proc in self._procs.get(job_id, []):
            try:
                os.killpg(os.getpgid(proc.pid), signal.SIGTERM)
            except (ProcessLookupError, PermissionError):
                pass
        if job['status'] in (job_lib.JobStatus.PENDING,):
            self.jobs.set_status(job_id, job_lib.JobStatus.CANCELLED)
        return web.json_response({'cancelled': job_id})

    async def h_logs(self, req: web.Request) -> web.StreamResponse:
        """Stream rank logs; ?follow=1 tails until the job ends
        (reference sky/skylet/log_lib.py tailing)."""
        await failpoints.hit_async('agent.tail')
        job_id = int(req.match_info['job_id'])
        job = self.jobs.get(job_id)
        if job is None:
            return web.json_response({'error': 'not found'}, status=404)
        follow = req.query.get('follow', '0') == '1'
        rank = int(req.query.get('rank', 0))
        resp = web.StreamResponse()
        resp.content_type = 'text/plain'
        await resp.prepare(req)
        log_dir = job['log_dir']
        setup_path = os.path.join(log_dir, f'rank{rank}_setup.log')
        run_path = os.path.join(log_dir, f'rank{rank}_run.log')
        # Stream both files concurrently by position: the setup phase only
        # writes the setup log, the run phase only the run log, so a single
        # interleaved pass moves from one to the other as the job advances
        # (a pure per-file loop would sit on the setup log until the job
        # *ends* and never show live run output).
        pos = {setup_path: 0, run_path: 0}

        async def drain(path: str) -> None:
            if not os.path.exists(path):
                return
            with open(path, 'rb') as f:
                f.seek(pos[path])
                chunk = f.read()
            if chunk:
                pos[path] += len(chunk)
                await resp.write(chunk)

        while True:
            job = self.jobs.get(job_id)
            await drain(setup_path)
            await drain(run_path)
            if not follow or job['status'].is_terminal():
                # Final drain catches writes between read and status check.
                await drain(setup_path)
                await drain(run_path)
                break
            await asyncio.sleep(0.2)
        await resp.write_eof()
        return resp

    async def h_exec(self, req: web.Request) -> web.Response:
        """Synchronous command on all hosts (setup / pre-exec stages)."""
        body = await req.json()
        self._exec_counter += 1
        exec_id = -self._exec_counter
        log_dir = os.path.join(self.cluster_dir, 'exec_logs',
                               str(int(time.time() * 1000)))
        try:
            rcs = await self._fan_out(exec_id, body['cmd'],
                                      body.get('envs', {}),
                                      log_dir, 'exec')
        finally:
            procs = self._procs.pop(exec_id, None) or []
            self._prune_pgids(p.pid for p in procs)
        tails = {}
        for r in range(len(rcs)):
            p = os.path.join(log_dir, f'rank{r}_exec.log')
            if os.path.exists(p):
                with open(p, encoding='utf-8', errors='replace') as f:
                    tails[r] = f.read()[-2000:]
        return web.json_response({'returncodes': rcs, 'tails': tails})

    async def h_run_rank(self, req: web.Request) -> web.Response:
        """Peer-host execution endpoint (host mode fan-out target)."""
        body = await req.json()
        log_dir = os.path.join(self.cluster_dir, 'job_logs',
                               str(body['job_id']))
        job_id = int(body['job_id'])
        rc = await self._run_rank(
            job_id, self.host_rank, body['cmd'],
            body.get('envs', {}),
            os.path.join(log_dir,
                         f'rank{self.host_rank}_{body["phase"]}.log'))
        # Peers have no _run_job finally: clean this call's handle and
        # reaper entry here or they accumulate for the agent's lifetime.
        procs = self._procs.get(job_id, [])
        done = [p for p in procs if p.returncode is not None]
        for p in done:
            procs.remove(p)
        if not procs:
            self._procs.pop(job_id, None)
        self._prune_pgids(p.pid for p in done)
        return web.json_response({'returncode': rc})

    async def h_autostop(self, req: web.Request) -> web.Response:
        if req.method == 'POST':
            body = await req.json()
            body['set_at'] = time.time()
            with open(self._autostop_file, 'w', encoding='utf-8') as f:
                json.dump(body, f)
            return web.json_response({'ok': True})
        return web.json_response(self._autostop_config())

    def make_app(self) -> web.Application:
        @web.middleware
        async def _trace(request: web.Request, handler):
            # Mutating endpoints get an agent-hop span parented to the
            # caller's traceparent header. GET/stream endpoints (log
            # tails can live for a job's whole runtime) stay untraced.
            if not trace_lib.enabled() or request.method != 'POST':
                return await handler(request)
            # Span names use the ROUTE TEMPLATE ('/cancel/{job_id}'),
            # not the raw path — per-id names would mint a metric label
            # per job and exhaust the server's label-cardinality cap.
            try:
                name = request.match_info.route.resource.canonical
            except AttributeError:
                name = request.path
            with trace_lib.context_from(
                    request.headers.get(trace_lib.HEADER)), \
                    trace_lib.span(f'agent.{name}'):
                resp = await handler(request)
            # Ship promptly (local store or the API server's collector);
            # off-loop: flush may do file/HTTP IO.
            await asyncio.get_event_loop().run_in_executor(
                None, trace_lib.flush)
            return resp

        @web.middleware
        async def _auth(request: web.Request, handler):
            if request.path == '/health':
                return await handler(request)
            token = self._auth_token()
            if not token:
                # Secure by default: an agent provisioned without a
                # token serves liveness only. Every provider generates
                # one; hitting this means a hand-rolled config.
                return web.json_response(
                    {'error': 'agent has no auth token configured; '
                              'only /health is served'}, status=403)
            hdr = request.headers.get('Authorization', '')
            presented = hdr[len('Bearer '):] if \
                hdr.startswith('Bearer ') else ''
            if not hmac.compare_digest(presented, token):
                return web.json_response({'error': 'forbidden'},
                                         status=403)
            return await handler(request)

        app = web.Application(middlewares=[_auth, _trace])
        app.router.add_get('/health', self.h_health)
        app.router.add_post('/submit', self.h_submit)
        app.router.add_get('/jobs', self.h_jobs)
        app.router.add_get('/jobs/{job_id}', self.h_job)
        app.router.add_post('/cancel/{job_id}', self.h_cancel)
        app.router.add_get('/logs/{job_id}', self.h_logs)
        app.router.add_post('/exec', self.h_exec)
        app.router.add_post('/run_rank', self.h_run_rank)
        app.router.add_route('*', '/autostop', self.h_autostop)
        return app


async def _main(cluster_dir: str, host: str, port: int) -> None:
    agent = Agent(cluster_dir)
    app = agent.make_app()
    runner = web.AppRunner(app)
    await runner.setup()
    ssl_ctx = None
    if agent.tls_cert_pem and agent.tls_key_pem:
        from skypilot_tpu.utils import tls
        ssl_ctx = tls.server_context(agent.tls_cert_pem,
                                     agent.tls_key_pem,
                                     workdir=agent.cluster_dir)
    site = web.TCPSite(runner, host, port, ssl_context=ssl_ctx)
    await site.start()
    actual_port = site._server.sockets[0].getsockname()[1]  # noqa: SLF001
    scheme = 'https' if ssl_ctx is not None else 'http'
    # Atomic publish: provisioners poll for this file and JSON-parse it the
    # moment it appears, so a plain open/write races with the reader.
    agent_json = os.path.join(cluster_dir, 'agent.json')
    tmp = agent_json + '.tmp'
    with open(tmp, 'w', encoding='utf-8') as f:
        json.dump({'url': f'{scheme}://{host}:{actual_port}',
                   'pid': os.getpid(),
                   'cert_fingerprint': agent.cert_fingerprint}, f)
    os.replace(tmp, agent_json)
    loop = asyncio.get_event_loop()
    loop.create_task(agent.scheduler_loop())
    loop.create_task(agent.autostop_loop())
    loop.create_task(agent.heartbeat_loop())
    loop.create_task(agent.log_gc_loop())
    while True:
        await asyncio.sleep(3600)


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument('--cluster-dir', required=True)
    parser.add_argument('--host', default='127.0.0.1')
    parser.add_argument('--port', type=int, default=0)
    args = parser.parse_args()
    try:
        asyncio.run(_main(args.cluster_dir, args.host, args.port))
    except KeyboardInterrupt:
        sys.exit(0)


if __name__ == '__main__':
    main()
