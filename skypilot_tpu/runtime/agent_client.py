"""Synchronous client for the on-host agent.

Counterpart of the reference's ``SkyletClient`` (reference
cloud_vm_ray_backend.py:2718, gRPC over an SSH tunnel at :2305). Here the
transport is plain HTTP to the head host's agent; on GCP the agent port is
reachable over the VPC (or an SSH tunnel, handled by the backend).
"""
from __future__ import annotations

import os
import time
from typing import Any, Dict, Iterator, List, Optional

import requests

from skypilot_tpu import exceptions
from skypilot_tpu.observability import trace as trace_lib
from skypilot_tpu.utils import common
from skypilot_tpu.utils import tls


class AgentClient:
    def __init__(self, url: str, timeout: float = 30.0,
                 token: Optional[str] = None,
                 cert_fingerprint: Optional[str] = None):
        self.url = url.rstrip('/')
        self.timeout = timeout
        # Per-cluster shared secret (provision-time generated, rides
        # ClusterInfo.provider_config['agent_token']); the agent 403s
        # every endpoint but /health without it.
        self.token = token
        # Cluster cert pin (provider_config['agent_cert_fingerprint']).
        # https agent URLs are only spoken to through the pinned
        # session — an unpinned https URL fails closed (utils/tls.py).
        self._session = tls.pinned_session(cert_fingerprint)

    @classmethod
    def for_info(cls, info, timeout: float = 30.0,
                 host: Optional[int] = None) -> 'AgentClient':
        """Client for a cluster's head agent (or host index ``host``),
        with the cluster token + cert pin wired through."""
        h = info.hosts[host] if host is not None else info.head
        return cls(h.agent_url, timeout=timeout,
                   token=info.provider_config.get('agent_token'),
                   cert_fingerprint=info.provider_config.get(
                       'agent_cert_fingerprint'))

    def _headers(self) -> dict:
        headers = ({'Authorization': f'Bearer {self.token}'}
                   if self.token else {})
        # Trace context crosses the pinned agent channel as the same
        # traceparent header the API server consumed (no-op when
        # tracing is off).
        return trace_lib.inject_headers(headers)

    def wait_healthy(self, timeout: Optional[float] = None
                     ) -> Dict[str, Any]:
        if timeout is None:
            # Env-tunable: CI boxes under heavy contention (xdist on few
            # cores) need longer than production's 60s to fork+import an
            # agent process.
            timeout = float(os.environ.get('SKY_TPU_AGENT_WAIT_S', '60'))
        deadline = time.time() + timeout
        last_err: Optional[Exception] = None
        with trace_lib.span('agent_client.wait_healthy', url=self.url):
            while time.time() < deadline:
                try:
                    r = self._session.get(f'{self.url}/health', timeout=5)
                    if r.ok:
                        return r.json()
                except requests.RequestException as e:
                    last_err = e
                time.sleep(0.5)
        raise exceptions.ClusterNotUpError(
            f'Agent at {self.url} not healthy after {timeout}s: {last_err}')

    def health(self) -> Dict[str, Any]:
        r = self._session.get(f'{self.url}/health', timeout=self.timeout)
        r.raise_for_status()
        return r.json()

    def submit(self, name: str, run: str, setup: Optional[str] = None,
               envs: Optional[Dict[str, str]] = None) -> int:
        with trace_lib.span('agent_client.submit', job=name):
            r = self._session.post(f'{self.url}/submit', json={
                'name': name, 'run': run, 'setup': setup,
                'envs': envs or {},
            }, headers=self._headers(), timeout=self.timeout)
            r.raise_for_status()
            return int(r.json()['job_id'])

    def job_status(self, job_id: int) -> common.JobStatus:
        r = self._session.get(f'{self.url}/jobs/{job_id}',
                         headers=self._headers(), timeout=self.timeout)
        if r.status_code == 404:
            raise exceptions.JobNotFoundError(f'job {job_id}')
        r.raise_for_status()
        return common.JobStatus(r.json()['status'])

    def jobs(self) -> List[Dict[str, Any]]:
        r = self._session.get(f'{self.url}/jobs', headers=self._headers(),
                         timeout=self.timeout)
        r.raise_for_status()
        return r.json()['jobs']

    def cancel(self, job_id: int) -> None:
        r = self._session.post(f'{self.url}/cancel/{job_id}',
                          headers=self._headers(), timeout=self.timeout)
        if r.status_code == 404:
            raise exceptions.JobNotFoundError(f'job {job_id}')
        r.raise_for_status()

    def exec_sync(self, cmd: str,
                  envs: Optional[Dict[str, str]] = None,
                  timeout: float = 600.0) -> Dict[str, Any]:
        with trace_lib.span('agent_client.exec'):
            r = self._session.post(f'{self.url}/exec',
                              json={'cmd': cmd, 'envs': envs or {}},
                              headers=self._headers(), timeout=timeout)
            r.raise_for_status()
            return r.json()

    def tail_logs(self, job_id: int, *, follow: bool = True,
                  rank: int = 0) -> Iterator[bytes]:
        with self._session.get(
                f'{self.url}/logs/{job_id}',
                params={'follow': '1' if follow else '0', 'rank': rank},
                headers=self._headers(), stream=True, timeout=None) as r:
            if r.status_code == 404:
                raise exceptions.JobNotFoundError(f'job {job_id}')
            r.raise_for_status()
            yield from r.iter_content(chunk_size=None)

    def wait_job(self, job_id: int,
                 timeout: float = 3600.0) -> common.JobStatus:
        deadline = time.time() + timeout
        while time.time() < deadline:
            st = self.job_status(job_id)
            if st.is_terminal():
                return st
            time.sleep(0.5)
        raise TimeoutError(f'job {job_id} still running after {timeout}s')

    def set_autostop(self, idle_minutes: int, down: bool = False) -> None:
        r = self._session.post(f'{self.url}/autostop', json={
            'idle_minutes': idle_minutes, 'down': down,
        }, headers=self._headers(), timeout=self.timeout)
        r.raise_for_status()
