"""Synchronous client for the on-host agent.

Counterpart of the reference's ``SkyletClient`` (reference
cloud_vm_ray_backend.py:2718, gRPC over an SSH tunnel at :2305). Here the
transport is plain HTTP to the head host's agent; on GCP the agent port is
reachable over the VPC (or an SSH tunnel, handled by the backend).

Every call goes through the shared ``Retrier`` (utils/retry.py):
connection trouble and agent 5xx responses — an OOM-killed agent
restarting, a TLS handshake racing an agent upgrade, an injected
failpoint — are transient and retried with full-jitter backoff; 4xx
responses are contract errors and surface immediately. The agent's
mutating endpoints are safe to retry: /submit carries a per-logical-call
``submit_id`` the agent dedups on (a response lost after the job row
committed returns the same job on retry), /cancel and /autostop are
idempotent.
"""
from __future__ import annotations

import os
import time
import uuid
from typing import Any, Dict, Iterator, List, Optional

import requests

from skypilot_tpu import exceptions
from skypilot_tpu.observability import trace as trace_lib
from skypilot_tpu.utils import common
from skypilot_tpu.utils import failpoints
from skypilot_tpu.utils import retry as retry_lib
from skypilot_tpu.utils import tls


def _retry_on(exc: BaseException) -> bool:
    """Transient for the agent hop: transport failures, agent 5xx, and
    client-side injected chaos (`agent_client.request` failpoint)."""
    if isinstance(exc, requests.HTTPError):
        resp = exc.response
        return resp is not None and resp.status_code >= 500
    return isinstance(exc, (requests.ConnectionError, requests.Timeout,
                            ConnectionError, TimeoutError, OSError,
                            failpoints.FailpointError))


class AgentClient:
    def __init__(self, url: str, timeout: float = 30.0,
                 token: Optional[str] = None,
                 cert_fingerprint: Optional[str] = None):
        self.url = url.rstrip('/')
        self.timeout = timeout
        # Per-cluster shared secret (provision-time generated, rides
        # ClusterInfo.provider_config['agent_token']); the agent 403s
        # every endpoint but /health without it.
        self.token = token
        # Cluster cert pin (provider_config['agent_cert_fingerprint']).
        # https agent URLs are only spoken to through the pinned
        # session — an unpinned https URL fails closed (utils/tls.py).
        self._session = tls.pinned_session(cert_fingerprint)

    @classmethod
    def for_info(cls, info, timeout: float = 30.0,
                 host: Optional[int] = None) -> 'AgentClient':
        """Client for a cluster's head agent (or host index ``host``),
        with the cluster token + cert pin wired through."""
        h = info.hosts[host] if host is not None else info.head
        return cls(h.agent_url, timeout=timeout,
                   token=info.provider_config.get('agent_token'),
                   cert_fingerprint=info.provider_config.get(
                       'agent_cert_fingerprint'))

    def _headers(self) -> dict:
        headers = ({'Authorization': f'Bearer {self.token}'}
                   if self.token else {})
        # Trace context crosses the pinned agent channel as the same
        # traceparent header the API server consumed (no-op when
        # tracing is off).
        return trace_lib.inject_headers(headers)

    def _retrier(self, op: str,
                 deadline_s: Optional[float] = None) -> retry_lib.Retrier:
        return retry_lib.Retrier(
            f'agent.{op}',
            max_attempts=int(os.environ.get('SKY_TPU_AGENT_RETRIES',
                                            '4')),
            base_delay_s=float(os.environ.get(
                'SKY_TPU_AGENT_RETRY_BASE_S', '0.2')),
            max_delay_s=2.0,
            deadline_s=deadline_s,
            transient=(), retry_on=_retry_on,
            fatal=(exceptions.JobNotFoundError,))

    def _request(self, method: str, path: str, *, op: str,
                 timeout: Optional[float],
                 not_found: Optional[str] = None,
                 **kw: Any) -> requests.Response:
        def _once() -> requests.Response:
            # Client-side chaos seam — fires in the CALLER's process
            # (controller, provisioner), complementing the agent-side
            # `agent.*` sites which fire in the agent daemon.
            failpoints.hit('agent_client.request')
            r = self._session.request(method, f'{self.url}{path}',
                                      timeout=timeout, **kw)
            if r.status_code == 404 and not_found is not None:
                raise exceptions.JobNotFoundError(not_found)
            r.raise_for_status()
            return r
        return self._retrier(op).call(_once)

    def wait_healthy(self, timeout: Optional[float] = None
                     ) -> Dict[str, Any]:
        if timeout is None:
            # Env-tunable: CI boxes under heavy contention (xdist on few
            # cores) need longer than production's 60s to fork+import an
            # agent process.
            timeout = float(os.environ.get('SKY_TPU_AGENT_WAIT_S', '60'))
        with trace_lib.span('agent_client.wait_healthy', url=self.url):
            # Deadline-bound Retrier with a tight delay cap: the old
            # 0.5s polling cadence, now with jitter + trace events. The
            # attempt budget is sized WELL past the deadline (mean
            # jittered delay is 0.25s, so timeout*4 attempts would
            # exhaust before the deadline about half the time) — the
            # deadline is the sole effective bound. Unlike normal
            # calls, EVERY HTTP failure (including 4xx — e.g. a token
            # or ingress still settling mid-bootstrap) keeps polling:
            # only the deadline concludes an agent is not coming up.
            r = retry_lib.Retrier(
                'agent.wait_healthy',
                max_attempts=max(16, int(timeout * 16)),
                base_delay_s=0.5, max_delay_s=0.5, deadline_s=timeout,
                transient=(requests.RequestException, ConnectionError,
                           TimeoutError, OSError))
            try:
                def _once() -> requests.Response:
                    resp = self._session.get(f'{self.url}/health',
                                             timeout=5)
                    resp.raise_for_status()
                    return resp
                return r.call(_once).json()
            except Exception as e:  # noqa: BLE001 — deadline exhausted
                raise exceptions.ClusterNotUpError(
                    f'Agent at {self.url} not healthy after {timeout}s: '
                    f'{e}') from e

    def health(self) -> Dict[str, Any]:
        return self._request('GET', '/health', op='health',
                             timeout=self.timeout).json()

    def submit(self, name: str, run: str, setup: Optional[str] = None,
               envs: Optional[Dict[str, str]] = None) -> int:
        with trace_lib.span('agent_client.submit', job=name):
            # One submit_id per LOGICAL submit, constant across retries:
            # if a response is lost after the agent committed the job
            # row, the retried POST returns the same job instead of
            # creating a duplicate (the agent dedups on it).
            r = self._request('POST', '/submit', op='submit',
                              json={'name': name, 'run': run,
                                    'setup': setup, 'envs': envs or {},
                                    'submit_id': uuid.uuid4().hex},
                              headers=self._headers(),
                              timeout=self.timeout)
            return int(r.json()['job_id'])

    def job_status(self, job_id: int) -> common.JobStatus:
        r = self._request('GET', f'/jobs/{job_id}', op='job_status',
                          not_found=f'job {job_id}',
                          headers=self._headers(), timeout=self.timeout)
        return common.JobStatus(r.json()['status'])

    def jobs(self) -> List[Dict[str, Any]]:
        r = self._request('GET', '/jobs', op='jobs',
                          headers=self._headers(), timeout=self.timeout)
        return r.json()['jobs']

    def cancel(self, job_id: int) -> None:
        self._request('POST', f'/cancel/{job_id}', op='cancel',
                      not_found=f'job {job_id}',
                      headers=self._headers(), timeout=self.timeout)

    def exec_sync(self, cmd: str,
                  envs: Optional[Dict[str, str]] = None,
                  timeout: float = 600.0) -> Dict[str, Any]:
        with trace_lib.span('agent_client.exec'):
            # NOT retried at the HTTP layer: /exec runs an arbitrary
            # command — re-POSTing after an ambiguous failure could run
            # it twice. Callers own exec retry semantics.
            failpoints.hit('agent_client.request')
            r = self._session.post(f'{self.url}/exec',
                                   json={'cmd': cmd, 'envs': envs or {}},
                                   headers=self._headers(),
                                   timeout=timeout)
            r.raise_for_status()
            return r.json()

    def tail_logs(self, job_id: int, *, follow: bool = True,
                  rank: int = 0) -> Iterator[bytes]:
        # Connection establishment is retried (the Retrier wraps the
        # request + status check); a stream dropped MID-iteration is
        # not — the caller decides whether replayed bytes are acceptable.
        r = self._request(
            'GET', f'/logs/{job_id}', op='tail_logs',
            not_found=f'job {job_id}',
            params={'follow': '1' if follow else '0', 'rank': rank},
            headers=self._headers(), stream=True, timeout=None)
        with r:
            yield from r.iter_content(chunk_size=None)

    def wait_job(self, job_id: int,
                 timeout: float = 3600.0) -> common.JobStatus:
        deadline = time.time() + timeout
        while time.time() < deadline:
            st = self.job_status(job_id)
            if st.is_terminal():
                return st
            time.sleep(0.5)
        raise TimeoutError(f'job {job_id} still running after {timeout}s')

    def set_autostop(self, idle_minutes: int, down: bool = False) -> None:
        self._request('POST', '/autostop', op='autostop',
                      json={'idle_minutes': idle_minutes, 'down': down},
                      headers=self._headers(), timeout=self.timeout)
