"""Build-on-first-use for the native runtime components (native/*.cc).

TPU hosts get the framework via pip/rsync, not a container image with
prebuilt binaries, so natives compile lazily with the host toolchain
(g++ is universally present on TPU VM images) and cache under
``$SKY_TPU_HOME/bin``. A missing toolchain degrades gracefully: callers
treat ``None`` as "native unavailable" and fall back to pure-Python
behavior.
"""
from __future__ import annotations

import hashlib
import logging
import os
import shutil
import subprocess
from typing import Optional

from skypilot_tpu.utils import common

logger = logging.getLogger(__name__)

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), 'native')


def _bin_dir() -> str:
    d = os.path.join(common.base_dir(), 'bin')
    os.makedirs(d, exist_ok=True)
    return d


def ensure_binary(name: str) -> Optional[str]:
    """Path to the compiled native binary, building if needed.

    Cache key includes the source hash so edited sources rebuild.
    Returns None when the source or a C++ toolchain is unavailable.
    """
    src = os.path.join(_NATIVE_DIR, f'{name}.cc')
    if not os.path.exists(src):
        return None
    with open(src, 'rb') as f:
        digest = hashlib.sha256(f.read()).hexdigest()[:12]
    out = os.path.join(_bin_dir(), f'{name}-{digest}')
    if os.path.exists(out):
        return out
    cxx = shutil.which('g++') or shutil.which('c++')
    if cxx is None:
        logger.warning('no C++ toolchain; native %s unavailable', name)
        return None
    # Per-process tmp name: concurrent builders (e.g. two agents starting
    # at once) must not share one tmp or the loser's replace() fails.
    tmp = f'{out}.{os.getpid()}.tmp'
    proc = subprocess.run(
        [cxx, '-O2', '-std=c++17', '-o', tmp, src],
        capture_output=True, text=True)
    if proc.returncode != 0:
        logger.warning('building native %s failed:\n%s', name,
                       proc.stderr)
        return None
    os.replace(tmp, out)   # atomic rename; last writer wins
    return out


def ensure_reaper() -> Optional[str]:
    return ensure_binary('reaper')
