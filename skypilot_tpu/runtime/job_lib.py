"""Per-cluster job queue (sqlite) — the agent's bookkeeping.

Counterpart of the reference's ``sky/skylet/job_lib.py`` (JobStatus at :156,
FIFOScheduler at :353, ``update_job_status`` at :814 with PID-based
liveness, ``is_cluster_idle`` at :981). Lives on the head host (or in the
fake slice's cluster dir locally); the agent is the only writer.
"""
from __future__ import annotations

import json
import os
import sqlite3
import time
from typing import Any, Dict, List, Optional

from skypilot_tpu.utils import common

JobStatus = common.JobStatus

_SCHEMA = """
CREATE TABLE IF NOT EXISTS jobs (
    job_id INTEGER PRIMARY KEY AUTOINCREMENT,
    name TEXT,
    status TEXT,
    submitted_at REAL,
    started_at REAL,
    ended_at REAL,
    run_cmd TEXT,
    setup_cmd TEXT,
    envs_json TEXT,
    num_hosts INTEGER,
    log_dir TEXT,
    pids_json TEXT
);
"""


class JobTable:
    def __init__(self, db_path: str):
        os.makedirs(os.path.dirname(db_path), exist_ok=True)
        self._conn = sqlite3.connect(db_path, timeout=30.0,
                                     check_same_thread=False)
        self._conn.execute('PRAGMA journal_mode=WAL')
        self._conn.executescript(_SCHEMA)
        self._conn.row_factory = sqlite3.Row

    def add_job(self, name: str, run_cmd: str, setup_cmd: Optional[str],
                envs: Dict[str, str], num_hosts: int, log_dir: str) -> int:
        cur = self._conn.execute(
            'INSERT INTO jobs (name, status, submitted_at, run_cmd, '
            'setup_cmd, envs_json, num_hosts, log_dir, pids_json) '
            'VALUES (?,?,?,?,?,?,?,?,?)',
            (name, JobStatus.PENDING.value, time.time(), run_cmd,
             setup_cmd or '', json.dumps(envs), num_hosts, log_dir, '[]'))
        self._conn.commit()
        return int(cur.lastrowid)

    def set_status(self, job_id: int, status: JobStatus) -> None:
        cols = {'status': status.value}
        if status == JobStatus.RUNNING:
            cols['started_at'] = time.time()
        elif status.is_terminal():
            cols['ended_at'] = time.time()
        sets = ', '.join(f'{k}=?' for k in cols)
        self._conn.execute(f'UPDATE jobs SET {sets} WHERE job_id=?',
                           (*cols.values(), job_id))
        self._conn.commit()

    def set_pids(self, job_id: int, pids: List[int]) -> None:
        self._conn.execute('UPDATE jobs SET pids_json=? WHERE job_id=?',
                           (json.dumps(pids), job_id))
        self._conn.commit()

    def get(self, job_id: int) -> Optional[Dict[str, Any]]:
        row = self._conn.execute('SELECT * FROM jobs WHERE job_id=?',
                                 (job_id,)).fetchone()
        return self._to_dict(row) if row else None

    def list_jobs(self) -> List[Dict[str, Any]]:
        rows = self._conn.execute(
            'SELECT * FROM jobs ORDER BY job_id DESC').fetchall()
        return [self._to_dict(r) for r in rows]

    def next_pending(self) -> Optional[Dict[str, Any]]:
        """FIFO: oldest PENDING job (reference FIFOScheduler, job_lib.py:353)."""
        row = self._conn.execute(
            "SELECT * FROM jobs WHERE status=? ORDER BY job_id LIMIT 1",
            (JobStatus.PENDING.value,)).fetchone()
        return self._to_dict(row) if row else None

    def running_jobs(self) -> List[Dict[str, Any]]:
        rows = self._conn.execute(
            'SELECT * FROM jobs WHERE status IN (?,?,?)',
            (JobStatus.RUNNING.value, JobStatus.SETTING_UP.value,
             JobStatus.INIT.value)).fetchall()
        return [self._to_dict(r) for r in rows]

    def is_idle(self) -> bool:
        """No pending or running jobs (reference is_cluster_idle,
        job_lib.py:981)."""
        row = self._conn.execute(
            'SELECT COUNT(*) c FROM jobs WHERE status IN (?,?,?,?)',
            (JobStatus.PENDING.value, JobStatus.RUNNING.value,
             JobStatus.SETTING_UP.value, JobStatus.INIT.value)).fetchone()
        return row['c'] == 0

    def last_activity(self) -> float:
        """Most recent job end/submit time (autostop idleness anchor)."""
        row = self._conn.execute(
            'SELECT MAX(MAX(COALESCE(ended_at,0)), '
            'MAX(COALESCE(submitted_at,0))) m FROM jobs').fetchone()
        return float(row['m'] or 0.0)

    @staticmethod
    def _to_dict(row: sqlite3.Row) -> Dict[str, Any]:
        d = dict(row)
        d['envs'] = json.loads(d.pop('envs_json') or '{}')
        d['pids'] = json.loads(d.pop('pids_json') or '[]')
        d['status'] = JobStatus(d['status'])
        return d
