"""Usage telemetry: local by default, HTTP sink optional, opt-out.

Counterpart of the reference's ``sky/usage/usage_lib.py`` (messages +
heartbeats shipped to a hosted Loki, ``_send_to_loki`` :427, heartbeat
:554, the ``@usage_lib.entrypoint`` decorator :615). The record stream
lands in ``~/.sky_tpu/usage/usage.jsonl`` — one JSON line per entrypoint
call with op name, duration, outcome, and framework version; the
periodic heartbeat (server daemon) adds control-plane gauges (cluster/
job/service counts). ``SKY_TPU_USAGE_SINK`` redirects: a filesystem path
appends there instead, an ``http(s)://`` URL POSTs each record as JSON
(Loki-push-compatible shape: ``{"streams":[{"stream":{...},"values":
[[ts_ns, line]]}]}``) — best-effort, never blocking the product.
``SKY_TPU_DISABLE_USAGE=1`` turns it off entirely.
"""
from __future__ import annotations

import functools
import json
import os
import time
import uuid
from typing import Any, Callable, Dict, Optional

from skypilot_tpu.utils import common

DISABLE_ENV = 'SKY_TPU_DISABLE_USAGE'
SINK_ENV = 'SKY_TPU_USAGE_SINK'

_run_id = uuid.uuid4().hex[:12]


def disabled() -> bool:
    return os.environ.get(DISABLE_ENV, '').lower() in ('1', 'true')


def _sink_path() -> str:
    custom = os.environ.get(SINK_ENV)
    if custom:
        return custom
    d = os.path.join(common.base_dir(), 'usage')
    os.makedirs(d, exist_ok=True)
    return os.path.join(d, 'usage.jsonl')


_http_queue = None
_http_thread = None


def _http_worker() -> None:
    import urllib.request
    while True:
        url, line = _http_queue.get()
        try:
            payload = json.dumps({'streams': [{
                'stream': {'source': 'skypilot-tpu', 'op': line['op']},
                'values': [[str(int(line['ts'] * 1e9)),
                            json.dumps(line)]],
            }]}).encode()
            req = urllib.request.Request(
                url, data=payload,
                headers={'Content-Type': 'application/json'})
            with urllib.request.urlopen(req, timeout=2.0):
                pass
        except Exception:  # noqa: BLE001 — drop; telemetry never breaks
            pass
        finally:
            _http_queue.task_done()


def _post_http(url: str, line: Dict[str, Any]) -> None:
    """Loki-push-shaped POST (reference _send_to_loki,
    sky/usage/usage_lib.py:427), shipped from a background thread so a
    slow/blackholed sink never stalls the calling entrypoint. Bounded
    queue: overflow drops records rather than blocking."""
    global _http_queue, _http_thread
    import atexit
    import queue
    import threading
    if _http_thread is None or not _http_thread.is_alive():
        _http_queue = queue.Queue(maxsize=1024)
        _http_thread = threading.Thread(target=_http_worker,
                                        daemon=True,
                                        name='usage-http-sink')
        _http_thread.start()
        # Short-lived CLI processes would otherwise exit before the
        # daemon thread ships anything; a bounded flush at exit keeps
        # the common telemetry source (one-shot CLI ops) reporting.
        atexit.register(flush_http_sink, 2.0)
    try:
        _http_queue.put_nowait((url, line))
    except queue.Full:
        pass


def flush_http_sink(timeout: float = 5.0) -> None:
    """Drain pending HTTP records (tests / graceful shutdown)."""
    if _http_queue is None:
        return
    deadline = time.time() + timeout
    while not _http_queue.empty() and time.time() < deadline:
        time.sleep(0.02)
    # One in-flight record may remain; give the join a moment.
    t0 = time.time()
    while (_http_queue.unfinished_tasks and
           time.time() - t0 < max(0.0, deadline - time.time()) + 0.5):
        time.sleep(0.02)


def record(op: str, duration_s: float, outcome: str,
           extra: Optional[Dict[str, Any]] = None) -> None:
    if disabled():
        return
    import skypilot_tpu
    line = {
        'ts': time.time(),
        'run_id': _run_id,
        'op': op,
        'duration_s': round(duration_s, 4),
        'outcome': outcome,
        'version': skypilot_tpu.__version__,
    }
    if extra:
        line.update(extra)
    sink = os.environ.get(SINK_ENV, '')
    try:
        if sink.startswith(('http://', 'https://')):
            _post_http(sink, line)
        else:
            with open(_sink_path(), 'a', encoding='utf-8') as f:
                f.write(json.dumps(line) + '\n')
    except Exception:  # noqa: BLE001 — telemetry must never break
        pass           # the product


def entrypoint(fn: Callable = None, *,
               name: Optional[str] = None) -> Callable:
    """Decorator recording each call (reference @usage_lib.entrypoint)."""
    def wrap(f: Callable) -> Callable:
        op = name or f.__qualname__

        @functools.wraps(f)
        def inner(*a, **kw):
            t0 = time.time()
            try:
                result = f(*a, **kw)
            except BaseException as e:
                record(op, time.time() - t0,
                       f'error:{type(e).__name__}')
                raise
            record(op, time.time() - t0, 'ok')
            return result
        return inner

    return wrap(fn) if fn is not None else wrap


def heartbeat() -> None:
    """Periodic liveness record with control-plane gauges (reference
    UsageHeartbeatReportEvent, sky/skylet/events.py:153 +
    usage_lib.py:554); called by server daemons."""
    gauges: Dict[str, Any] = {}
    try:
        from skypilot_tpu import state
        gauges['clusters'] = len(state.get_clusters())
    except Exception:  # noqa: BLE001 — gauge collection is best-effort
        pass
    try:
        from skypilot_tpu.jobs import state as jobs_state
        jobs = jobs_state.get_jobs()
        gauges['managed_jobs'] = len(jobs)
        gauges['managed_jobs_active'] = sum(
            1 for j in jobs if not j['status'].is_terminal())
    except Exception:  # noqa: BLE001
        pass
    try:
        from skypilot_tpu.serve import state as serve_state
        gauges['services'] = len(serve_state.get_services())
    except Exception:  # noqa: BLE001
        pass
    record('heartbeat', 0.0, 'ok', extra=gauges)
