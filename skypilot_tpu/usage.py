"""Usage telemetry: local, append-only, opt-out.

Counterpart of the reference's ``sky/usage/usage_lib.py`` (messages +
heartbeats shipped to a hosted Loki, ``_send_to_loki`` :427, the
``@usage_lib.entrypoint`` decorator :615). This environment has zero
egress, so the same record stream lands in
``~/.sky_tpu/usage/usage.jsonl`` — one JSON line per entrypoint call with
op name, duration, outcome, and framework version. A deployment that
wants central collection points ``SKY_TPU_USAGE_SINK`` at a different
writable path (or a future HTTP sink). ``SKY_TPU_DISABLE_USAGE=1`` turns
it off entirely.
"""
from __future__ import annotations

import functools
import json
import os
import time
import uuid
from typing import Any, Callable, Dict, Optional

from skypilot_tpu.utils import common

DISABLE_ENV = 'SKY_TPU_DISABLE_USAGE'
SINK_ENV = 'SKY_TPU_USAGE_SINK'

_run_id = uuid.uuid4().hex[:12]


def disabled() -> bool:
    return os.environ.get(DISABLE_ENV, '').lower() in ('1', 'true')


def _sink_path() -> str:
    custom = os.environ.get(SINK_ENV)
    if custom:
        return custom
    d = os.path.join(common.base_dir(), 'usage')
    os.makedirs(d, exist_ok=True)
    return os.path.join(d, 'usage.jsonl')


def record(op: str, duration_s: float, outcome: str,
           extra: Optional[Dict[str, Any]] = None) -> None:
    if disabled():
        return
    import skypilot_tpu
    line = {
        'ts': time.time(),
        'run_id': _run_id,
        'op': op,
        'duration_s': round(duration_s, 4),
        'outcome': outcome,
        'version': skypilot_tpu.__version__,
    }
    if extra:
        line.update(extra)
    try:
        with open(_sink_path(), 'a', encoding='utf-8') as f:
            f.write(json.dumps(line) + '\n')
    except OSError:
        pass   # telemetry must never break the product


def entrypoint(fn: Callable = None, *,
               name: Optional[str] = None) -> Callable:
    """Decorator recording each call (reference @usage_lib.entrypoint)."""
    def wrap(f: Callable) -> Callable:
        op = name or f.__qualname__

        @functools.wraps(f)
        def inner(*a, **kw):
            t0 = time.time()
            try:
                result = f(*a, **kw)
            except BaseException as e:
                record(op, time.time() - t0,
                       f'error:{type(e).__name__}')
                raise
            record(op, time.time() - t0, 'ok')
            return result
        return inner

    return wrap(fn) if fn is not None else wrap


def heartbeat() -> None:
    """Periodic liveness record (reference UsageHeartbeatReportEvent,
    sky/skylet/events.py:153); called by server daemons."""
    record('heartbeat', 0.0, 'ok')
