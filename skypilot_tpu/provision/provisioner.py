"""Provision orchestration: candidate loop with failover + cleanup.

Counterpart of the reference's two-layer structure: ``bulk_provision``
(reference sky/provision/provisioner.py:122) for one attempt, and the
``RetryingVmProvisioner`` failover loop (reference
cloud_vm_ray_backend.py:736, ``_retry_zones`` :942,
``provision_with_retries`` :1661) that walks optimizer candidates, blocks
failed zones/regions, and aggregates the failover history into
``ResourcesUnavailableError``.

TPU-first simplification: a slice allocates atomically, so there is no
partial-gang cleanup *within* a zone attempt — either the node exists
(terminate it on later failure) or it does not. Retry granularity is
whole-slice (SURVEY.md §7 "hard parts").
"""
from __future__ import annotations

import logging
import os
from typing import List, Optional, Tuple

from skypilot_tpu import catalog
from skypilot_tpu import exceptions
from skypilot_tpu import provision
from skypilot_tpu import resources as resources_lib
from skypilot_tpu.provision.common import ClusterInfo, ProvisionConfig
from skypilot_tpu.runtime import agent_client
from skypilot_tpu.utils import failpoints
from skypilot_tpu.utils import retry as retry_lib

logger = logging.getLogger(__name__)


def _create_retrier() -> retry_lib.Retrier:
    """Retry policy for the cloud-API create call itself: transient
    transport trouble (and injected chaos) is retried *within* one
    placement attempt; ProvisionError/CapacityError are NOT transient —
    those are the failover loop's granularity, not the Retrier's."""
    return retry_lib.Retrier(
        'provision.create',
        max_attempts=int(os.environ.get(
            'SKY_TPU_PROVISION_RETRIES', '3')),
        base_delay_s=float(os.environ.get(
            'SKY_TPU_PROVISION_RETRY_BASE_S', '0.5')),
        deadline_s=60.0,
        transient=(ConnectionError, TimeoutError, OSError,
                   failpoints.FailpointError))


def _make_config(candidate: catalog.Candidate,
                 cluster_name: str,
                 res: resources_lib.Resources,
                 data_disks: 'List[str]' = ()) -> ProvisionConfig:
    from skypilot_tpu import config as config_lib
    provider_config = dict(
        config_lib.get_nested((candidate.cloud,), {}) or {})
    provider_config['zone'] = candidate.zone
    if candidate.cloud == 'kubernetes':
        # k8s candidates encode context as region, namespace as zone
        # (catalog._k8s_candidate); the provider reads these keys.
        if candidate.region != 'in-cluster':
            provider_config['context'] = candidate.region
        provider_config['namespace'] = candidate.zone
    if candidate.cloud == 'slurm' and candidate.region != 'default':
        # slurm candidates encode the partition as region
        # (catalog._slurm_candidate); a user-pinned partition must reach
        # the sbatch script.
        provider_config['partition'] = candidate.region
    return ProvisionConfig(
        cluster_name=cluster_name,
        region=candidate.region,
        zone=candidate.zone,
        instance_type=candidate.instance_type,
        num_hosts=candidate.num_hosts,
        tpu_slice=candidate.tpu.name if candidate.tpu else None,
        num_slices=res.num_slices,
        use_spot=candidate.use_spot,
        disk_size_gb=res.disk_size_gb,
        image_id=res.image_id,
        runtime_version=res.runtime_version,
        ports=res.ports,
        labels=res.labels,
        data_disks=list(data_disks),
        provider_config=provider_config,
    )


def bulk_provision(candidate: catalog.Candidate,
                   cluster_name: str,
                   res: resources_lib.Resources,
                   *,
                   wait_agent: bool = True,
                   data_disks: 'List[str]' = ()) -> ClusterInfo:
    """One atomic provisioning attempt: create slice, wait for hosts, wait
    for the head agent (reference provisioner.py:122 + wait_for_ssh :389 —
    the agent replaces SSH-wait as the readiness signal)."""
    config = _make_config(candidate, cluster_name, res, data_disks)

    def _create() -> ClusterInfo:
        # Failpoint inside the retried callable: an `@N` budget is
        # consumed per attempt, so `provision.create=error:1@2` means
        # "fail the first two create calls, then succeed".
        failpoints.hit('provision.create')
        return provision.run_instances(candidate.cloud, config)

    info = _create_retrier().call(_create)
    failpoints.hit('provision.bootstrap')
    provision.wait_instances(candidate.cloud, cluster_name,
                             info.provider_config)
    info.cost_per_hour = candidate.cost_per_hour * res.num_slices
    if wait_agent and info.head.agent_url:
        # EVERY host's agent, not just the head: the head fans job ranks
        # out to peers' /run_rank the moment a job is submitted — a peer
        # still booting turns the first job into a spurious rank failure
        # (caught by the fake-ssh multihost e2e). One SHARED deadline:
        # hosts boot concurrently, so a dead host must fail the attempt
        # after ~one budget, not num_hosts budgets in sequence.
        import os as os_lib
        import time as time_lib
        budget = float(os_lib.environ.get('SKY_TPU_AGENT_WAIT_S', '60'))
        deadline = time_lib.time() + budget
        fp = info.provider_config.get('agent_cert_fingerprint')
        for host in info.hosts:
            if host.agent_url:
                agent_client.AgentClient(
                    host.agent_url, cert_fingerprint=fp).wait_healthy(
                        timeout=max(5.0, deadline - time_lib.time()))
    if res.ports:
        provision.open_ports(candidate.cloud, cluster_name, res.ports,
                             info.provider_config)
    return info


def provision_with_retries(
    cluster_name: str,
    res: resources_lib.Resources,
    candidates: List[catalog.Candidate],
    data_disks: 'List[str]' = (),
) -> Tuple[ClusterInfo, catalog.Candidate]:
    """Walk candidates cheapest-first with zone/region blocklisting.

    Raises ResourcesUnavailableError carrying the full failover history
    when every candidate fails (consumed by managed-jobs recovery
    strategies).
    """
    failover_history: List[Exception] = []
    blocked_zones: set = set()
    blocked_regions: set = set()
    last_err: Optional[Exception] = None
    for cand in candidates:
        if (cand.cloud, cand.region) in blocked_regions:
            continue
        if (cand.cloud, cand.region, cand.zone) in blocked_zones:
            continue
        try:
            logger.info('Provisioning %s as %s', cand, cluster_name)
            info = bulk_provision(cand, cluster_name, res,
                                  data_disks=data_disks)
            return info, cand
        except exceptions.QuotaExceededError as e:
            # Quota is regional: block the whole region.
            failover_history.append(e)
            blocked_regions.add((cand.cloud, cand.region))
            last_err = e
        except exceptions.ProvisionError as e:
            failover_history.append(e)
            if not e.retryable:
                raise exceptions.ResourcesUnavailableError(
                    f'Non-retryable provisioning failure for '
                    f'{cluster_name}: {e}',
                    failover_history=failover_history) from e
            blocked_zones.add((cand.cloud, cand.region, cand.zone))
            if e.blocked_region:
                blocked_regions.add((cand.cloud, e.blocked_region))
            last_err = e
            _cleanup_partial(cand.cloud, cluster_name,
                             _make_config(cand, cluster_name,
                                          res).provider_config)
        except exceptions.NoCloudAccessError as e:
            failover_history.append(e)
            # Credentials missing: no point trying other zones of the
            # same cloud.
            blocked_regions.update(
                {(cand.cloud, c.region) for c in candidates
                 if c.cloud == cand.cloud})
            last_err = e
    raise exceptions.ResourcesUnavailableError(
        f'Failed to provision {cluster_name!r} on all '
        f'{len(candidates)} candidate placements. Last error: {last_err}',
        failover_history=failover_history)


def _cleanup_partial(cloud: str, cluster_name: str,
                     provider_config: dict) -> None:
    """Best-effort teardown of a half-created slice before failover.

    `provider_config` must carry the attempt's zone/project — an empty
    config would make GCP lookup fail silently and leak a billed node.
    """
    try:
        info = provision.get_cluster_info(cloud, cluster_name,
                                          provider_config)
        if info is not None:
            provision.terminate_instances(cloud, cluster_name,
                                          info.provider_config)
    except Exception:  # noqa: BLE001 — cleanup must not mask the cause
        logger.warning('Partial-cleanup of %s failed', cluster_name,
                       exc_info=True)
