"""SSH node-pool provisioner: bare-metal hosts as gang-ready slices.

Counterpart of the reference's ``sky/provision/ssh`` + ``sky ssh up``
(sky/ssh_node_pools/core.py:144): "provisioning" a pool is health-checking
every host and bootstrapping the on-host agent — the hosts already exist.
Terminate releases the pool (stops the agent) but never destroys hosts.

Two modes per pool (``mode:`` in the pool config):
- ``ssh`` (default): reach hosts over SSH, rsync the framework, start the
  agent on host 0 (reference instance_setup start_skylet analog).
- ``process``: hosts are simulated by local processes exactly like the
  ``local`` cloud — the offline test path for pool logic.
"""
from __future__ import annotations

import json
import os
import secrets
import shutil
import time
from typing import Any, Dict, List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu.provision.common import (ClusterInfo, HostInfo,
                                           ProvisionConfig)
from skypilot_tpu.provision.local import instance as local_instance
from skypilot_tpu.ssh_node_pools import SSHNodePoolManager
from skypilot_tpu.utils import command_runner
from skypilot_tpu.utils import common
from skypilot_tpu.utils import tls

AGENT_PORT = 46590   # same convention as the GCP provider
AGENT_DIR = '/opt/sky_tpu/cluster'


def _cluster_dir(cluster_name: str) -> str:
    return os.path.join(common.clusters_dir(), cluster_name)


def _pool_of(config_or_provider) -> Dict[str, Any]:
    if isinstance(config_or_provider, ProvisionConfig):
        pool_name = (config_or_provider.instance_type or
                     config_or_provider.provider_config.get('pool'))
    else:
        pool_name = config_or_provider.get('pool')
    if not pool_name:
        raise exceptions.ProvisionError(
            '[ssh] no pool named (set resources.instance_type to the '
            'pool name)', retryable=False)
    return {'name': pool_name, **SSHNodePoolManager().get_pool(pool_name)}


def _runner_for(host: str, pool: Dict[str, Any]
                ) -> command_runner.CommandRunner:
    return command_runner.SSHCommandRunner(
        host, user=pool['user'],
        key_path=pool.get('identity_file'),
        password=pool.get('password'))


def _health_check(pool: Dict[str, Any]) -> List[str]:
    """Every host must answer; a gang with a dead member is no gang."""
    dead = []
    for host in pool['hosts']:
        rc, _, _ = _runner_for(host, pool).run(
            'true', timeout=15, check=False)
        if rc != 0:
            dead.append(host)
    return dead


def run_instances(config: ProvisionConfig) -> ClusterInfo:
    if config.num_slices > 1:
        raise exceptions.ProvisionError(
            'multislice (num_slices > 1) is supported on the gcp and '
            'local providers only', retryable=False)
    pool = _pool_of(config)
    cdir = _cluster_dir(config.cluster_name)
    os.makedirs(cdir, exist_ok=True)
    # Per-cluster agent secret (reused on idempotent re-provision so a
    # live agent keeps serving; see runtime/agent.py auth middleware).
    prev_meta = _meta(cdir) or {}
    token = (config.provider_config.get('agent_token') or
             prev_meta.get('agent_token') or
             secrets.token_hex(16))
    # Cluster TLS pair (utils/tls.py): generated once per cluster,
    # reused on idempotent re-provision so live agents keep their pin.
    # When the pair is MINTED here (fresh cluster, or a pre-TLS cluster
    # being re-provisioned), any already-running agent is still serving
    # plain HTTP — the bootstrap must restart it, or the https:// URLs
    # this provision reports would point at live plain-HTTP agents.
    had_cert = bool(prev_meta.get('tls_cert_pem') and
                    prev_meta.get('tls_key_pem'))
    cert_pem, key_pem = tls.ensure_cluster_cert(
        prev_meta, config.cluster_name, 'tls_cert_pem', 'tls_key_pem')
    cert_minted = bool(cert_pem) and not had_cert
    mode = pool.get('mode', 'ssh')
    if mode == 'process':
        # Delegate host simulation to the local provider, then overlay
        # pool identity on the result.
        num_hosts = len(pool['hosts'])
        meta = {
            'cluster_name': config.cluster_name,
            'region': pool.get('region', 'pool'),
            'zone': pool['name'],
            'instance_type': pool['name'],
            'tpu_slice': pool.get('accelerator'),
            'num_hosts': num_hosts,
            'use_spot': False,
            'created_at': time.time(),
            'pool': pool['name'],
            'mode': 'process',
            'agent_token': token,
            'tls_cert_pem': cert_pem,
            'tls_key_pem': key_pem,
        }
        for r in range(num_hosts):
            hd = os.path.join(cdir, f'host{r}')
            os.makedirs(os.path.join(hd, 'workdir'), exist_ok=True)
            with open(os.path.join(hd, 'state'), 'w',
                      encoding='utf-8') as f:
                f.write('RUNNING')
        with open(os.path.join(cdir, 'meta.json'), 'w',
                  encoding='utf-8') as f:
            json.dump(meta, f)
        local_instance._start_agent(config.cluster_name)  # noqa: SLF001
        return get_cluster_info(config.cluster_name,
                                {'pool': pool['name']})
    dead = _health_check(pool)
    if dead:
        raise exceptions.ProvisionError(
            f'[ssh] pool {pool["name"]!r} hosts unreachable: {dead}',
            retryable=True)
    _bootstrap_agent(config.cluster_name, pool, token, cert_pem, key_pem,
                     force_restart=cert_minted)
    meta = {
        'cluster_name': config.cluster_name,
        'region': pool.get('region', 'pool'),
        'zone': pool['name'],
        'instance_type': pool['name'],
        'tpu_slice': pool.get('accelerator'),
        'num_hosts': len(pool['hosts']),
        'use_spot': False,
        'created_at': time.time(),
        'pool': pool['name'],
        'mode': 'ssh',
        'agent_token': token,
        'tls_cert_pem': cert_pem,
        'tls_key_pem': key_pem,
    }
    with open(os.path.join(cdir, 'meta.json'), 'w', encoding='utf-8') as f:
        json.dump(meta, f)
    return get_cluster_info(config.cluster_name, {'pool': pool['name']})


def _bootstrap_agent(cluster_name: str, pool: Dict[str, Any],
                     token: str, cert_pem: Optional[str] = None,
                     key_pem: Optional[str] = None,
                     force_restart: bool = False) -> None:
    """Push the framework + start an agent on EVERY host (mirrors the GCP
    provider's _install_agents: head's agent fans job ranks out to peers'
    /run_rank, so each host needs a listening agent).

    ``force_restart`` kills a running agent before the idempotence
    guard: used when the serving scheme changes under it (TLS upgrade —
    a freshly minted cert only takes effect on restart)."""
    import skypilot_tpu
    from skypilot_tpu.provision import common as provision_common
    pkg_root = os.path.dirname(os.path.dirname(
        os.path.abspath(skypilot_tpu.__file__)))
    hosts = list(pool['hosts'])
    stop_snippet = (provision_common.agent_stop_snippet(
        f'{AGENT_DIR}/agent.pid') if force_restart else '')
    for rank, host in enumerate(hosts):
        runner = _runner_for(host, pool)
        runner.run(f'sudo mkdir -p {AGENT_DIR} && sudo chown -R '
                   f'$(whoami) /opt/sky_tpu', timeout=30, check=True)
        runner.rsync(f'{pkg_root}/skypilot_tpu/',
                     f'{AGENT_DIR}/skypilot_tpu/')
        agent_config = {
            'cluster_name': cluster_name,
            'mode': 'host',
            'auth_token': token,
            'tls_cert_pem': cert_pem,
            'tls_key_pem': key_pem,
            'host_rank': rank,
            'host_ips': hosts,
            'num_hosts': len(hosts),
            'tpu_slice': pool.get('accelerator'),
            'peer_agent_urls': [
                f'{tls.scheme_for(cert_pem)}://{h}:{AGENT_PORT}'
                for i, h in enumerate(hosts) if i != rank
            ] if rank == 0 else [],
            # NOTE: no password here — agent_config.json lands on every
            # host and the agent never sshes outward.
            'provider_config': {'pool': pool['name'],
                                'ssh_user': pool['user'],
                                'ssh_key': pool.get('identity_file')},
        }
        # Distributed tracing reaches remote agents through their
        # config, not the provisioner's environment.
        from skypilot_tpu.observability import trace as trace_lib
        agent_config.update(trace_lib.agent_trace_config())
        cfg_json = json.dumps(agent_config).replace("'", "'\\''")
        # Idempotence probe via pidfile, NOT pgrep: the remote shell's
        # own cmdline contains the agent start text, so any
        # `pgrep -f <agent pattern> || start` one-liner SELF-MATCHES and
        # the agent never starts on a fresh host (found by the fake-ssh
        # multihost e2e). The /proc cmdline check guards against PID
        # reuse after a reboot (stale pidfile pointing at an unrelated
        # process would otherwise suppress the restart forever).
        runner.run(
            f"echo '{cfg_json}' > {AGENT_DIR}/agent_config.json && "
            f'{stop_snippet}'
            f'AP="$(cat {AGENT_DIR}/agent.pid 2>/dev/null)"; '
            f'if ! {{ kill -0 "$AP" 2>/dev/null && '
            f'grep -q runtime.agent "/proc/$AP/cmdline" 2>/dev/null; }}; '
            f'then '
            f'PYTHONPATH={AGENT_DIR} nohup python3 -m '
            f'skypilot_tpu.runtime.agent --cluster-dir {AGENT_DIR} '
            f'--host 0.0.0.0 --port {AGENT_PORT} '
            f'> {AGENT_DIR}/agent.log 2>&1 & '
            f'echo $! > {AGENT_DIR}/agent.pid; fi',
            timeout=60, check=True)


def stop_instances(cluster_name: str,
                   provider_config: Dict[str, Any]) -> None:
    cdir = _cluster_dir(cluster_name)
    meta = _meta(cdir)
    if meta and meta.get('mode') == 'process':
        local_instance.stop_instances(cluster_name, provider_config)
        return
    # Bare metal "stop" = stop the agents; hosts stay up. A deleted
    # pool config must not wedge the cluster in a half-stopped state
    # (terminate has the same guard).
    try:
        pool = _pool_of({'pool': (meta or {}).get('pool') or
                         provider_config.get('pool')})
    except exceptions.SkyTpuError:
        return
    for host in pool['hosts']:
        _runner_for(host, pool).run(
            'pkill -f skypilot_tpu.runtime.agent || true', timeout=30,
            check=False)


def start_instances(cluster_name: str,
                    provider_config: Dict[str, Any]) -> ClusterInfo:
    cdir = _cluster_dir(cluster_name)
    meta = _meta(cdir)
    if meta is None:
        raise exceptions.ClusterDoesNotExist(cluster_name)
    if meta.get('mode') == 'process':
        local_instance.start_instances(cluster_name, provider_config)
        return get_cluster_info(cluster_name, provider_config)
    pool = _pool_of({'pool': meta['pool']})
    _bootstrap_agent(cluster_name, pool, meta['agent_token'],
                     meta.get('tls_cert_pem'), meta.get('tls_key_pem'))
    return get_cluster_info(cluster_name, provider_config)


def terminate_instances(cluster_name: str,
                        provider_config: Dict[str, Any]) -> None:
    cdir = _cluster_dir(cluster_name)
    meta = _meta(cdir)
    if meta and meta.get('mode') == 'process':
        local_instance.terminate_instances(cluster_name, provider_config)
        return
    if meta:
        try:
            pool = _pool_of({'pool': meta['pool']})
            for host in pool['hosts']:
                _runner_for(host, pool).run(
                    f'pkill -f skypilot_tpu.runtime.agent || true; '
                    f'rm -rf {AGENT_DIR}', timeout=30, check=False)
        except exceptions.SkyTpuError:
            pass   # pool config gone; release bookkeeping anyway
    shutil.rmtree(cdir, ignore_errors=True)


def wait_instances(cluster_name: str, provider_config: Dict[str, Any],
                   state: str = 'RUNNING') -> None:
    info = get_cluster_info(cluster_name, provider_config)
    if info is None:
        raise exceptions.ProvisionError(
            f'[ssh] cluster {cluster_name} does not exist')
    bad = [h for h in info.hosts if h.state != state]
    if bad:
        raise exceptions.ProvisionError(
            f'[ssh] hosts not {state}: {[h.host_id for h in bad]}')


def _meta(cdir: str) -> Optional[Dict[str, Any]]:
    p = os.path.join(cdir, 'meta.json')
    if not os.path.exists(p):
        return None
    with open(p, encoding='utf-8') as f:
        return json.load(f)


def get_cluster_info(cluster_name: str,
                     provider_config: Dict[str, Any]
                     ) -> Optional[ClusterInfo]:
    cdir = _cluster_dir(cluster_name)
    meta = _meta(cdir)
    if meta is None:
        return None
    if meta.get('mode') == 'process':
        info = local_instance.get_cluster_info(cluster_name,
                                               provider_config)
        if info is None:
            return None
        # Pool identity overlays the local simulation.
        info.cloud = 'ssh'
        info.instance_type = meta['instance_type']
        info.tpu_slice = meta.get('tpu_slice')
        return info
    pool = _pool_of({'pool': meta['pool']})
    # Per-HOST agent URLs: each host runs its own agent (the head fans
    # ranks out to them); provisioning waits on every one of them.
    scheme = tls.scheme_for(meta.get('tls_cert_pem'))
    hosts = [HostInfo(host_id=f'{cluster_name}-host{i}',
                      internal_ip=h, external_ip=h, state='RUNNING',
                      agent_url=f'{scheme}://{h}:{AGENT_PORT}')
             for i, h in enumerate(pool['hosts'])]
    return ClusterInfo(
        cluster_name=cluster_name, cloud='ssh',
        region=meta['region'], zone=meta['zone'], hosts=hosts,
        tpu_slice=meta.get('tpu_slice'),
        instance_type=meta['instance_type'], use_spot=False,
        cost_per_hour=0.0,
        provider_config={'pool': meta['pool'],
                         'ssh_user': pool.get('user'),
                         'ssh_key': pool.get('identity_file'),
                         'ssh_password': pool.get('password'),
                         'agent_token': meta.get('agent_token'),
                         'agent_cert_fingerprint': tls.fingerprint_of_pem(
                             meta.get('tls_cert_pem'))})


def open_ports(cluster_name: str, ports,
               provider_config: Dict[str, Any]) -> None:
    del cluster_name, ports, provider_config   # firewalling is the
    # pool operator's concern on bare metal
