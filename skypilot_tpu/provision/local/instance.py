"""Local fake-slice provisioner: N-host TPU slices as local process groups.

The reference's offline-testing analog is ``mock_aws_backend`` (reference
tests/conftest.py:33) — a moto-mocked cloud. Here the fake cloud is a
first-class provider: a "slice" is a directory tree under
``$SKY_TPU_HOME/clusters/<name>/`` with one ``host<i>/`` dir per worker, and
one agent process (local-slice mode) that simulates gang execution by
spawning one subprocess per host with full `jax.distributed` env injected.
This makes multi-host gang logic, failover, autostop, managed jobs, and
serving testable on a laptop — SURVEY.md §4's "fake TPU slice" strategy.

Failure injection (for failover tests): set provider_config
``fail_regions`` to a list of regions that raise CapacityError, or create
the file ``<clusters_root>/fail_<region>`` at runtime.
"""
from __future__ import annotations

import json
import os
import secrets
import shutil
import signal
import subprocess
import sys
import time
from typing import Any, Dict, List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu import topology
from skypilot_tpu.provision.common import (ClusterInfo, HostInfo,
                                           ProvisionConfig)
from skypilot_tpu.utils import common
from skypilot_tpu.utils import tls

AGENT_START_TIMEOUT = 30.0


def _meta_of(cdir: str):
    p = os.path.join(cdir, 'meta.json')
    if not os.path.exists(p):
        return None
    try:
        with open(p, encoding='utf-8') as f:
            return json.load(f)
    except (json.JSONDecodeError, OSError):
        return None


def _cluster_dir(cluster_name: str) -> str:
    return os.path.join(common.clusters_dir(), cluster_name)


def _check_injected_failure(config: ProvisionConfig) -> None:
    fail_regions = config.provider_config.get('fail_regions', [])
    marker = os.path.join(common.clusters_dir(), f'fail_{config.region}')
    if config.region in fail_regions or os.path.exists(marker):
        raise exceptions.CapacityError(
            f'[local] injected stockout in {config.region}/{config.zone}',
            blocked_zone=config.zone, blocked_region=config.region)


def run_instances(config: ProvisionConfig) -> ClusterInfo:
    _check_injected_failure(config)
    cdir = _cluster_dir(config.cluster_name)
    os.makedirs(cdir, exist_ok=True)
    num_hosts = config.num_hosts          # per slice
    total_hosts = num_hosts * config.num_slices
    for r in range(total_hosts):
        hd = os.path.join(cdir, f'host{r}')
        os.makedirs(os.path.join(hd, 'workdir'), exist_ok=True)
        with open(os.path.join(hd, 'state'), 'w', encoding='utf-8') as f:
            f.write('RUNNING')
    # Per-cluster agent secret: reuse the existing one on idempotent
    # re-provision (a live agent keeps serving under it), generate on
    # first create. Callers that pass one (provisioner) win.
    token = config.provider_config.get('agent_token')
    prev = _meta_of(cdir)
    if not token:
        token = (prev or {}).get('agent_token') or secrets.token_hex(16)
    # Cluster TLS pair: generated once, reused across idempotent
    # re-provisions (a rotation would invalidate the live agent's pin
    # mid-flight); rides meta.json → agent_config.json like the token.
    # A pair minted HERE over a pre-TLS cluster must restart the live
    # plain-HTTP agent (same TLS upgrade path as the ssh/gcp
    # providers), or the reported https URL would point at it.
    had_cert = bool((prev or {}).get('tls_cert_pem') and
                    (prev or {}).get('tls_key_pem'))
    cert_pem, key_pem = tls.ensure_cluster_cert(
        prev or {}, config.cluster_name, 'tls_cert_pem', 'tls_key_pem')
    if prev is not None and bool(cert_pem) and not had_cert:
        _kill_agent(cdir)
    meta = {
        'cluster_name': config.cluster_name,
        'region': config.region,
        'zone': config.zone,
        'instance_type': config.instance_type,
        'tpu_slice': config.tpu_slice,
        'num_hosts': num_hosts,
        'num_slices': config.num_slices,
        'use_spot': config.use_spot,
        'created_at': time.time(),
        'agent_token': token,
        'tls_cert_pem': cert_pem,
        'tls_key_pem': key_pem,
    }
    with open(os.path.join(cdir, 'meta.json'), 'w', encoding='utf-8') as f:
        json.dump(meta, f)
    _start_agent(config.cluster_name)
    return get_cluster_info(config.cluster_name, config.provider_config)


def _start_agent(cluster_name: str) -> None:
    cdir = _cluster_dir(cluster_name)
    # Idempotent: reuse a live agent.
    existing = _agent_info(cdir)
    if existing is not None and _pid_alive(existing.get('pid', -1)):
        return
    with open(os.path.join(cdir, 'meta.json'), encoding='utf-8') as f:
        meta = json.load(f)
    num_slices = int(meta.get('num_slices', 1))
    agent_config = {
        'cluster_name': cluster_name,
        'mode': 'local-slice',
        'host_rank': 0,
        'host_ips': ['127.0.0.1'] * (meta['num_hosts'] * num_slices),
        'num_hosts': meta['num_hosts'],
        'num_slices': num_slices,
        'tpu_slice': meta.get('tpu_slice'),
        'auth_token': meta.get('agent_token'),
        'tls_cert_pem': meta.get('tls_cert_pem'),
        'tls_key_pem': meta.get('tls_key_pem'),
    }
    with open(os.path.join(cdir, 'agent_config.json'), 'w',
              encoding='utf-8') as f:
        json.dump(agent_config, f)
    agent_json = os.path.join(cdir, 'agent.json')
    if os.path.exists(agent_json):
        os.unlink(agent_json)
    with open(os.path.join(cdir, 'agent.log'), 'ab') as log:
        subprocess.Popen(
            [sys.executable, '-m', 'skypilot_tpu.runtime.agent',
             '--cluster-dir', cdir],
            stdout=log, stderr=subprocess.STDOUT,
            start_new_session=True,
            env={**os.environ, 'JAX_PLATFORMS': 'cpu'},
        )
    deadline = time.time() + AGENT_START_TIMEOUT
    while time.time() < deadline:
        info = _agent_info(cdir)
        if info is not None and info.get('url'):
            return
        time.sleep(0.1)
    raise exceptions.ProvisionError(
        f'[local] agent for {cluster_name} failed to start '
        f'(see {cdir}/agent.log)', retryable=False)


def _agent_info(cdir: str) -> Optional[Dict[str, Any]]:
    p = os.path.join(cdir, 'agent.json')
    if not os.path.exists(p):
        return None
    try:
        with open(p, encoding='utf-8') as f:
            return json.load(f)
    except (json.JSONDecodeError, OSError):
        return None


def _pid_alive(pid: int) -> bool:
    if pid <= 0:
        return False
    try:
        os.kill(pid, 0)
    except (ProcessLookupError, PermissionError):
        return False
    # A zombie answers kill(0) but is already dead — the agent's Popen
    # handle is never wait()ed (it outlives the provision call), so
    # every killed agent lingers as a zombie and a liveness wait that
    # counts zombies as alive burns its whole timeout on a corpse.
    try:
        with open(f'/proc/{pid}/stat', encoding='utf-8') as f:
            # Field 3 (after the parenthesized comm, which may itself
            # contain spaces): process state.
            return f.read().rpartition(')')[2].split()[0] != 'Z'
    except (OSError, IndexError):
        return True


def _kill_job_pgids(cdir: str) -> None:
    """Tear down the rank process groups the agent recorded.

    Rank processes run in their OWN sessions (start_new_session=True),
    so killing the agent's group does not reach them; the native reaper
    covers agent crashes, but teardown must not race it against the
    rmtree that deletes the pgid file (that race leaked long-lived
    serve replicas burning the CI box's only core)."""
    path = os.path.join(cdir, 'job_pgids')
    try:
        with open(path, encoding='utf-8') as f:
            pgids = [int(x) for x in f.read().split() if x.strip()]
    except (OSError, ValueError):
        return

    def _ours(pg: int) -> bool:
        # Pid-reuse guard: the file only ever grows while the agent
        # lives, so a finished job's pgid may now belong to an
        # unrelated process. Every rank we spawn carries
        # SKY_TPU_JOB_ID in its environment — only kill those.
        try:
            with open(f'/proc/{pg}/environ', 'rb') as f:
                return b'SKY_TPU_JOB_ID=' in f.read()
        except OSError:
            return False

    pgids = [pg for pg in pgids if _ours(pg)]
    for pg in pgids:
        try:
            os.killpg(pg, signal.SIGTERM)
        except (ProcessLookupError, PermissionError):
            pass
    time.sleep(0.2)
    for pg in pgids:
        try:
            os.killpg(pg, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            pass


def _kill_agent(cdir: str, timeout: float = 5.0) -> None:
    info = _agent_info(cdir)
    if not info:
        # No agent (already dead): still reap any rank processes it
        # left behind.
        _kill_job_pgids(cdir)
        return
    pid = info.get('pid', -1)
    if _pid_alive(pid):
        try:
            os.killpg(os.getpgid(pid), signal.SIGTERM)
        except (ProcessLookupError, PermissionError):
            try:
                os.kill(pid, signal.SIGTERM)
            except (ProcessLookupError, PermissionError):
                pass
        # Wait for actual death: a subsequent start must not observe a
        # half-dead agent and reuse its soon-to-be-closed port.
        deadline = time.time() + timeout
        while time.time() < deadline and _pid_alive(pid):
            time.sleep(0.05)
        if _pid_alive(pid):
            try:
                os.killpg(os.getpgid(pid), signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                pass
    # Rank process groups die AFTER the agent: if the agent saw its
    # ranks exit first it would record the job FAILED on the way down,
    # and the managed-jobs controller would read a preemption as a
    # user failure and refuse to recover.
    _kill_job_pgids(cdir)
    # Stale agent.json must not be mistaken for a live agent later.
    try:
        os.unlink(os.path.join(cdir, 'agent.json'))
    except FileNotFoundError:
        pass


def stop_instances(cluster_name: str,
                   provider_config: Dict[str, Any]) -> None:
    cdir = _cluster_dir(cluster_name)
    _kill_agent(cdir)
    for entry in os.listdir(cdir) if os.path.isdir(cdir) else []:
        if entry.startswith('host'):
            with open(os.path.join(cdir, entry, 'state'), 'w',
                      encoding='utf-8') as f:
                f.write('STOPPED')


def start_instances(cluster_name: str,
                    provider_config: Dict[str, Any]) -> ClusterInfo:
    cdir = _cluster_dir(cluster_name)
    if not os.path.isdir(cdir):
        raise exceptions.ClusterDoesNotExist(cluster_name)
    for entry in os.listdir(cdir):
        if entry.startswith('host'):
            with open(os.path.join(cdir, entry, 'state'), 'w',
                      encoding='utf-8') as f:
                f.write('RUNNING')
    trig = os.path.join(cdir, 'autostop_triggered.json')
    if os.path.exists(trig):
        os.unlink(trig)
    _start_agent(cluster_name)
    return get_cluster_info(cluster_name, provider_config)


def terminate_instances(cluster_name: str,
                        provider_config: Dict[str, Any]) -> None:
    cdir = _cluster_dir(cluster_name)
    _kill_agent(cdir)
    if os.path.isdir(cdir):
        shutil.rmtree(cdir, ignore_errors=True)


def wait_instances(cluster_name: str, provider_config: Dict[str, Any],
                   state: str = 'RUNNING') -> None:
    info = get_cluster_info(cluster_name, provider_config)
    if info is None:
        raise exceptions.ProvisionError(
            f'[local] cluster {cluster_name} does not exist')
    bad = [h for h in info.hosts if h.state != state]
    if bad:
        raise exceptions.ProvisionError(
            f'[local] hosts not {state}: {[h.host_id for h in bad]}')


def get_cluster_info(cluster_name: str,
                     provider_config: Dict[str, Any]
                     ) -> Optional[ClusterInfo]:
    cdir = _cluster_dir(cluster_name)
    meta_path = os.path.join(cdir, 'meta.json')
    if not os.path.exists(meta_path):
        return None
    with open(meta_path, encoding='utf-8') as f:
        meta = json.load(f)
    agent = _agent_info(cdir)
    agent_url = agent['url'] if agent else None
    hosts: List[HostInfo] = []
    total_hosts = meta['num_hosts'] * int(meta.get('num_slices', 1))
    for r in range(total_hosts):
        state_p = os.path.join(cdir, f'host{r}', 'state')
        st = 'TERMINATED'
        if os.path.exists(state_p):
            with open(state_p, encoding='utf-8') as f:
                st = f.read().strip()
        hosts.append(HostInfo(
            host_id=f'{cluster_name}-host{r}',
            internal_ip='127.0.0.1',
            external_ip='127.0.0.1',
            state=st,
            agent_url=agent_url))
    return ClusterInfo(
        cluster_name=cluster_name,
        cloud='local',
        region=meta['region'],
        zone=meta['zone'],
        hosts=hosts,
        tpu_slice=meta.get('tpu_slice'),
        num_slices=int(meta.get('num_slices', 1)),
        instance_type=meta['instance_type'],
        use_spot=meta.get('use_spot', False),
        cost_per_hour=0.0,
        provider_config={'cluster_dir': cdir,
                         'agent_token': meta.get('agent_token'),
                         'agent_cert_fingerprint': (
                             tls.fingerprint_of_pem(
                                 meta.get('tls_cert_pem')))})


def open_ports(cluster_name: str, ports,
               provider_config: Dict[str, Any]) -> None:
    del cluster_name, ports, provider_config  # no-op locally


# Loopback networking: every port is already reachable. The capability
# honesty test accepts a no-op only with this marker.
open_ports.trivially_open = True
