"""Cloud-agnostic provisioning dataclasses.

Counterpart of the reference's ``sky/provision/common.py`` (``ClusterInfo``/
``InstanceInfo``). TPU-first difference: a cluster *is* one slice (or one
VM); hosts are the slice's workers, gang-allocated atomically — there is no
per-node scale-up path.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional


@dataclasses.dataclass
class HostInfo:
    """One host (worker VM) of a slice."""
    host_id: str
    internal_ip: str
    external_ip: Optional[str] = None
    state: str = 'RUNNING'
    # Where the on-host agent listens (http://ip:port). For the local fake
    # cloud every host shares one agent that simulates the slice.
    agent_url: Optional[str] = None

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> 'HostInfo':
        return cls(**d)


@dataclasses.dataclass
class ClusterInfo:
    """Everything the backend needs to reach a provisioned slice."""
    cluster_name: str
    cloud: str
    region: str
    zone: str
    hosts: List[HostInfo]
    # TPU metadata (None for CPU/GPU clusters).
    tpu_slice: Optional[str] = None        # canonical slice name, 'v5e-16'
    # Multislice: hosts covers ALL slices (slice j owns hosts
    # [j*per_slice, (j+1)*per_slice)); DCN wiring via MEGASCALE env.
    num_slices: int = 1
    instance_type: Optional[str] = None
    use_spot: bool = False
    cost_per_hour: float = 0.0
    # Provider-specific extras (GCP project id, node name, local slice dir).
    provider_config: Dict[str, Any] = dataclasses.field(default_factory=dict)

    @property
    def head(self) -> HostInfo:
        return self.hosts[0]

    @property
    def num_hosts(self) -> int:
        return len(self.hosts)

    def to_dict(self) -> Dict[str, Any]:
        d = dataclasses.asdict(self)
        return d

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> 'ClusterInfo':
        d = dict(d)
        d['hosts'] = [HostInfo.from_dict(h) for h in d.get('hosts', [])]
        return cls(**d)


@dataclasses.dataclass
class ProvisionConfig:
    """Input to a provider's run_instances."""
    cluster_name: str
    region: str
    zone: str
    instance_type: str
    num_hosts: int                         # hosts per slice
    tpu_slice: Optional[str] = None        # canonical slice name
    num_slices: int = 1                    # multislice: N slices, one gang
    use_spot: bool = False
    disk_size_gb: int = 256
    image_id: Optional[str] = None
    runtime_version: Optional[str] = None  # TPU software version
    ports: List[int] = dataclasses.field(default_factory=list)
    labels: Dict[str, str] = dataclasses.field(default_factory=dict)
    # Pre-created zonal disk names to attach at node create (gcp-pd
    # volumes; the TPU API only attaches data disks at creation).
    data_disks: List[str] = dataclasses.field(default_factory=list)
    provider_config: Dict[str, Any] = dataclasses.field(default_factory=dict)


def agent_stop_snippet(pidfile: str) -> str:
    """Shell fragment that stops a running agent recorded in `pidfile`
    (and clears the pidfile), for bootstrap commands that must force an
    agent restart — e.g. the TLS upgrade path, where a freshly minted
    cluster cert is useless while a pre-TLS agent keeps serving plain
    HTTP behind the idempotence guard. /proc cmdline-checked so a
    recycled pid belonging to an unrelated process is never signalled.
    """
    return (
        f'AP="$(cat {pidfile} 2>/dev/null)"; '
        f'if grep -q runtime.agent "/proc/$AP/cmdline" 2>/dev/null; '
        f'then kill "$AP" 2>/dev/null; '
        f'for i in 1 2 3 4 5 6 7 8 9 10; do '
        f'kill -0 "$AP" 2>/dev/null || break; sleep 0.2; done; '
        f'kill -9 "$AP" 2>/dev/null; fi; '
        f'rm -f {pidfile}; ')
