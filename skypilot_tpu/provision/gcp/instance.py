"""GCP TPU provider: gang-allocates multi-host slices as single TPU nodes.

Implements the provider contract (see ``skypilot_tpu/provision/__init__``)
on top of the TPU REST client. One slice = one TPU node = one atomic
create/delete — the gang property the reference builds manually with Ray
placement groups falls out of the TPU API for free (reference's TPU path:
sky/provision/gcp/instance_utils.py:1208-1750).

The startup script installs+launches the on-host agent on every host; host 0
is the head (its agent fans out to peers over the slice's internal IPs).
"""
from __future__ import annotations

import logging
import os
import secrets
from typing import Any, Dict, List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu import topology
from skypilot_tpu.provision.common import (ClusterInfo, HostInfo,
                                           ProvisionConfig)
from skypilot_tpu.provision.gcp import tpu_api
from skypilot_tpu.utils import tls

logger = logging.getLogger(__name__)

DEFAULT_RUNTIME_VERSIONS = {
    'v2': 'tpu-ubuntu2204-base',
    'v3': 'tpu-ubuntu2204-base',
    'v4': 'tpu-ubuntu2204-base',
    'v5e': 'v2-alpha-tpuv5-lite',
    'v5p': 'v2-alpha-tpuv5',
    'v6e': 'v2-alpha-tpuv6e',
}

AGENT_PORT = 46590
AGENT_CLUSTER_DIR = '/opt/sky_tpu/cluster'
# The startup script only prepares the host (deps + dirs). The agent config
# needs the slice's internal IPs, which exist only after the node is READY —
# so `_install_agents` pushes the per-host config and starts the agent over
# SSH once `get_cluster_info` reports the endpoints.
_STARTUP_SCRIPT = """#!/bin/bash
set -e
mkdir -p /opt/sky_tpu/cluster
if ! command -v python3 >/dev/null; then apt-get update && apt-get install -y python3 python3-pip; fi
python3 -m pip install -q aiohttp requests pyyaml 2>/dev/null || true
"""


def _project(provider_config: Dict[str, Any]) -> str:
    project = (provider_config.get('project') or
               os.environ.get('GOOGLE_CLOUD_PROJECT') or
               os.environ.get('GCP_PROJECT'))
    if not project:
        raise exceptions.NoCloudAccessError(
            'GCP project not configured. Set gcp.project in '
            '~/.sky_tpu/config.yaml or GOOGLE_CLOUD_PROJECT.')
    return project


def _client(provider_config: Dict[str, Any]) -> tpu_api.TpuApiClient:
    return tpu_api.TpuApiClient(_project(provider_config))


def _node_names(cluster_name: str, num_slices: int) -> List[str]:
    """TPU node name per slice. Single slice keeps the bare cluster name
    (back-compat); multislice nodes are `<cluster>-s<j>`."""
    if num_slices <= 1:
        return [cluster_name]
    return [f'{cluster_name}-s{j}' for j in range(num_slices)]


def run_instances(config: ProvisionConfig) -> ClusterInfo:
    client = _client(config.provider_config)
    assert config.tpu_slice is not None, (
        'GCP provider currently supports TPU slices (CPU/GPU VMs via the '
        'compute provider are a future drop-in)')
    # Authorize the framework SSH key on every host of the slice.
    # setup_gcp_authentication is copy-on-write; rebind rather than
    # mutating the caller's dict in place.
    from skypilot_tpu import authentication
    config.provider_config = authentication.setup_gcp_authentication(
        config.provider_config)
    # Per-cluster agent secret: every agent endpoint but /health
    # requires it (the agent port is VPC-reachable once open_ports
    # runs). Rides provider_config so status refreshes preserve it.
    config.provider_config.setdefault('agent_token',
                                      secrets.token_hex(16))
    # Cluster TLS pair (utils/tls.py): the agent serves HTTPS and
    # clients pin the cert fingerprint, so the bearer token never rides
    # the VPC in clear. Lives in provider_config like the token so
    # status refreshes preserve it. A pair minted HERE (fresh cluster
    # or pre-TLS re-provision) only takes effect when the agents
    # (re)start with it — _install_agents must not let the pidfile
    # guard keep a plain-HTTP agent alive behind an https:// URL.
    had_cert = bool(config.provider_config.get('agent_tls_cert'))
    tls.ensure_cluster_cert(config.provider_config,
                            config.cluster_name)
    cert_minted = (not had_cert and
                   bool(config.provider_config.get('agent_tls_cert')))
    s = topology.parse_tpu(config.tpu_slice)
    runtime_version = (config.runtime_version or
                       DEFAULT_RUNTIME_VERSIONS[s.generation])
    # Multislice: one TPU node per slice, created in order; a failed
    # create tears down the already-created slices so the gang stays
    # atomic (partial multislice is useless to the job).
    names = _node_names(config.cluster_name, config.num_slices)
    created: List[str] = []
    try:
        for name in names:
            # Rollback must cover the IN-FLIGHT create too: a timeout
            # during create_node's operation-wait can leave the node
            # existing (billing, blocking the name) even though the call
            # raised — delete_node tolerates not-found.
            created.append(name)
            client.create_node(
                config.zone, name,
                accelerator_type=s.accelerator_type,
                runtime_version=runtime_version,
                spot=config.use_spot,
                labels={**config.labels,
                        'sky-tpu-cluster': config.cluster_name},
                startup_script=_STARTUP_SCRIPT,
                metadata=config.provider_config.get('metadata'),
                data_disks=config.data_disks,
                tags=[_net_tag(config.cluster_name)])
    except Exception:
        _rollback_created(client, config.zone, created)
        raise
    info = get_cluster_info(config.cluster_name, {
        **config.provider_config, 'zone': config.zone,
        'num_slices': config.num_slices})
    if info is None:
        # All creates returned but a node is gone on re-read. Same gang
        # atomicity rule as a failed create: tear down the survivors
        # before raising, or they bill until someone notices.
        _rollback_created(client, config.zone, created)
        raise exceptions.ProvisionError(
            f'TPU node {config.cluster_name} vanished after create')
    _install_agents(info, config, force_restart=cert_minted)
    return info


def _rollback_created(client: 'tpu_api.TpuApiClient', zone: str,
                      created: List[str]) -> None:
    """Best-effort delete of a partially-created multislice gang."""
    import time as time_lib
    for name in created:
        # The in-flight node may still be CREATING — GCP answers 409
        # to a delete racing its create op. Retry briefly; a node
        # that still survives is logged loud (it bills until removed)
        # rather than silently leaked.
        for attempt in range(4):
            try:
                client.delete_node(zone, name)
                break
            except Exception as de:  # noqa: BLE001 — rollback path
                if attempt == 3:
                    logger.error(
                        'multislice rollback could not delete TPU '
                        'node %s/%s: %s — delete it manually or '
                        'relaunch will fail with already-exists',
                        zone, name, de)
                else:
                    time_lib.sleep(10 * (attempt + 1))


def _install_agents(info: ClusterInfo, config: ProvisionConfig,
                    force_restart: bool = False) -> None:
    """Push per-host agent config + the framework itself, start agents.

    Host 0 is head; its agent fans out to the peers' /run_rank. Runs over
    SSH (the TPU VM's metadata-managed keys). ``force_restart`` stops a
    running agent first (TLS upgrade: the new cert needs a restart).
    """
    import json

    from skypilot_tpu.provision import common as provision_common
    from skypilot_tpu.utils import command_runner
    stop_snippet = (provision_common.agent_stop_snippet(
        '/opt/sky_tpu/agent.pid') if force_restart else '')
    ssh_user = config.provider_config.get('ssh_user', 'sky')
    key = config.provider_config.get('ssh_key', '~/.sky_tpu/keys/sky-key')
    internal_ips = [h.internal_ip for h in info.hosts]
    hosts_per_slice = len(info.hosts) // max(info.num_slices, 1)
    for rank, host in enumerate(info.hosts):
        agent_config = {
            'cluster_name': info.cluster_name,
            'mode': 'host',
            'auth_token': config.provider_config.get('agent_token'),
            'tls_cert_pem': config.provider_config.get('agent_tls_cert'),
            'tls_key_pem': config.provider_config.get('agent_tls_key'),
            # Global host index; the agent derives (slice_id, in-slice
            # rank) from it and num_hosts.
            'host_rank': rank,
            'host_ips': internal_ips,
            'num_hosts': hosts_per_slice,
            'num_slices': info.num_slices,
            'slice_id': rank // hosts_per_slice,
            'tpu_slice': info.tpu_slice,
            'peer_agent_urls': [
                f'{tls.scheme_for(config.provider_config.get("agent_tls_cert"))}'
                f'://{ip}:{AGENT_PORT}'
                for i, ip in enumerate(internal_ips) if i != rank
            ] if rank == 0 else [],
            'provider_config': dict(config.provider_config),
        }
        # Distributed tracing reaches remote agents through their
        # config, not the provisioner's environment.
        from skypilot_tpu.observability import trace as trace_lib
        agent_config.update(trace_lib.agent_trace_config())
        runner = command_runner.SSHCommandRunner(
            host.external_ip or host.internal_ip, user=ssh_user,
            key_path=key)
        cfg_json = json.dumps(agent_config).replace("'", "'\\''")
        # Idempotence probe via pidfile + /proc cmdline, NOT pgrep: the
        # remote shell's own cmdline contains the agent start text, so
        # `pgrep -f <pattern> || start` SELF-MATCHES and the agent never
        # starts on a fresh VM (same bug the fake-ssh multihost e2e
        # caught in the ssh provider; this was the last copy of it).
        runner.run(
            f"sudo mkdir -p {AGENT_CLUSTER_DIR} && "
            f"sudo chown -R $(whoami) /opt/sky_tpu && "
            f"echo '{cfg_json}' > {AGENT_CLUSTER_DIR}/agent_config.json && "
            f"(python3 -m pip show skypilot-tpu >/dev/null 2>&1 || "
            f"python3 -m pip install -q skypilot-tpu || true) && "
            f'{stop_snippet}'
            f'AP="$(cat /opt/sky_tpu/agent.pid 2>/dev/null)"; '
            f'if ! {{ kill -0 "$AP" 2>/dev/null && '
            f'grep -q runtime.agent "/proc/$AP/cmdline" 2>/dev/null; }}; '
            f'then '
            f"nohup python3 -m skypilot_tpu.runtime.agent "
            f"--cluster-dir {AGENT_CLUSTER_DIR} --host 0.0.0.0 "
            f"--port {AGENT_PORT} >/opt/sky_tpu/agent.log 2>&1 & "
            f"echo $! > /opt/sky_tpu/agent.pid; fi",
            check=True, timeout=120)


def get_cluster_info(cluster_name: str,
                     provider_config: Dict[str, Any]
                     ) -> Optional[ClusterInfo]:
    client = _client(provider_config)
    zone = provider_config['zone']
    num_slices = int(provider_config.get('num_slices', 1))
    hosts: List[HostInfo] = []
    state = 'UNKNOWN'
    node = None
    for name in _node_names(cluster_name, num_slices):
        try:
            node = client.get_node(zone, name)
        except exceptions.ClusterDoesNotExist:
            return None
        state = node.get('state', 'UNKNOWN')
        host_state = {'READY': 'RUNNING', 'STOPPED': 'STOPPED'}.get(
            state, state)
        scheme = tls.scheme_for(provider_config.get('agent_tls_cert'))
        for i, ep in enumerate(node.get('networkEndpoints', [])):
            external = (ep.get('accessConfig') or {}).get('externalIp')
            hosts.append(HostInfo(
                host_id=f'{name}-host{i}',
                internal_ip=ep.get('ipAddress', ''),
                external_ip=external,
                state=host_state,
                agent_url=(f'{scheme}://'
                           f'{external or ep.get("ipAddress", "")}:'
                           f'{AGENT_PORT}')))
    slice_name = None
    acc_type = node.get('acceleratorType') if node else None
    if acc_type:
        parsed = topology.parse_tpu(acc_type)
        slice_name = parsed.name if parsed else None
    return ClusterInfo(
        cluster_name=cluster_name,
        cloud='gcp',
        region=zone.rsplit('-', 1)[0],
        zone=zone,
        hosts=hosts,
        tpu_slice=slice_name,
        num_slices=num_slices,
        instance_type=acc_type,
        use_spot=bool(((node or {}).get('schedulingConfig') or
                       {}).get('spot')),
        provider_config={'project': client.project, 'zone': zone,
                         'node_state': state, 'num_slices': num_slices,
                         'agent_token':
                             provider_config.get('agent_token'),
                         'agent_tls_cert':
                             provider_config.get('agent_tls_cert'),
                         'agent_tls_key':
                             provider_config.get('agent_tls_key'),
                         'agent_cert_fingerprint': tls.fingerprint_of_pem(
                             provider_config.get('agent_tls_cert'))})


def _slices(provider_config: Dict[str, Any], cluster_name: str) -> List[str]:
    return _node_names(cluster_name,
                       int(provider_config.get('num_slices', 1)))


def stop_instances(cluster_name: str,
                   provider_config: Dict[str, Any]) -> None:
    client = _client(provider_config)
    for name in _slices(provider_config, cluster_name):
        client.stop_node(provider_config['zone'], name)


def start_instances(cluster_name: str,
                    provider_config: Dict[str, Any]) -> ClusterInfo:
    client = _client(provider_config)
    for name in _slices(provider_config, cluster_name):
        client.start_node(provider_config['zone'], name)
    info = get_cluster_info(cluster_name, provider_config)
    assert info is not None
    return info


def terminate_instances(cluster_name: str,
                        provider_config: Dict[str, Any]) -> None:
    client = _client(provider_config)
    for name in _slices(provider_config, cluster_name):
        client.delete_node(provider_config['zone'], name)
    try:
        cleanup_ports(cluster_name, provider_config)
    except Exception:  # noqa: BLE001 — an orphan allow-rule targets a
        # tag with no remaining VMs; never fail teardown over it.
        logger.warning('firewall rule cleanup failed for %s',
                       cluster_name, exc_info=True)


def wait_instances(cluster_name: str, provider_config: Dict[str, Any],
                   state: str = 'RUNNING') -> None:
    import time
    want = {'RUNNING': 'READY', 'STOPPED': 'STOPPED'}.get(state, state)
    client = _client(provider_config)
    deadline = time.time() + 600
    pending = list(_slices(provider_config, cluster_name))
    while time.time() < deadline:
        still = []
        for name in pending:
            node = client.get_node(provider_config['zone'], name)
            if node.get('state') in ('PREEMPTED', 'TERMINATED'):
                raise exceptions.ProvisionError(
                    f'TPU node {name} entered {node.get("state")}')
            if node.get('state') != want:
                still.append(name)
        pending = still
        if not pending:
            return
        time.sleep(10)
    raise exceptions.ProvisionTimeoutError(
        f'TPU nodes {pending} not {want} within 600s')


def _net_tag(cluster_name: str) -> str:
    import hashlib
    import re
    # Network-tag charset: lowercase letters, digits, dash. Capped at 57
    # so the '-ports' firewall-rule suffix still fits GCP's 63-char
    # limit. Truncated names get a hash suffix of the FULL name —
    # otherwise two long names sharing a prefix would collide and one
    # cluster's teardown would delete the other's firewall rule.
    tag = 'sky-tpu-' + re.sub(r'[^a-z0-9-]', '-', cluster_name.lower())
    if len(tag) <= 57:
        return tag.rstrip('-')
    h = hashlib.sha1(cluster_name.encode()).hexdigest()[:6]
    return f'{tag[:50].rstrip("-")}-{h}'


def _fw_rule_name(cluster_name: str) -> str:
    return _net_tag(cluster_name) + '-ports'


def open_ports(cluster_name: str, ports,
               provider_config: Dict[str, Any]) -> None:
    """Create/refresh the VPC firewall rule exposing ``ports`` on this
    cluster's VMs (targeted by the network tag set at create; reference
    sky/provision/gcp/config.py:424 firewall-rule shape). Without it a
    served endpoint is reachable only inside the VPC."""
    client = tpu_api.GceFirewallClient(_project(provider_config))
    client.ensure_rule(
        _fw_rule_name(cluster_name),
        network=provider_config.get('network', 'default'),
        ports=[str(p) for p in ports],
        target_tag=_net_tag(cluster_name))


def cleanup_ports(cluster_name: str,
                  provider_config: Dict[str, Any]) -> None:
    """Delete the cluster's firewall rule (no-op if none was created)."""
    client = tpu_api.GceFirewallClient(_project(provider_config))
    client.delete_rule(_fw_rule_name(cluster_name))
