"""Thin REST client for the Cloud TPU API (tpu.googleapis.com, v2).

The reference drives this API through its Ray-autoscaler-derived handler
``GCPTPUVMInstance`` (reference sky/provision/gcp/instance_utils.py:1208,
API constants :1222-1226, operation polling :1234). Here the client is
standalone: one TPU *node* is one slice (all hosts), which is exactly the
gang-allocation unit — no per-VM bookkeeping.

Auth: Application Default Credentials via google-auth. All calls raise
ProvisionError subclasses the failover loop understands.
"""
from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

import requests

from skypilot_tpu import exceptions

TPU_API = 'https://tpu.googleapis.com/v2'
OPERATION_POLL_INTERVAL = 5.0
OPERATION_TIMEOUT = 1800.0


class TpuApiClient:
    def __init__(self, project: str):
        self.project = project
        self._creds = None

    # -- auth ------------------------------------------------------------
    def _token(self) -> str:
        try:
            import google.auth
            import google.auth.transport.requests
        except ImportError as e:
            raise exceptions.NoCloudAccessError(
                f'google-auth unavailable: {e}') from e
        if self._creds is None:
            try:
                self._creds, _ = google.auth.default(
                    scopes=['https://www.googleapis.com/auth/cloud-platform'])
            except Exception as e:  # noqa: BLE001
                raise exceptions.NoCloudAccessError(
                    f'No GCP credentials: {e}') from e
        if not self._creds.valid:
            self._creds.refresh(
                google.auth.transport.requests.Request())
        return self._creds.token

    def _headers(self) -> Dict[str, str]:
        return {'Authorization': f'Bearer {self._token()}',
                'Content-Type': 'application/json'}

    def _request(self, method: str, url: str,
                 json_body: Optional[Dict[str, Any]] = None
                 ) -> Dict[str, Any]:
        resp = requests.request(method, url, headers=self._headers(),
                                json=json_body, timeout=60)
        if resp.status_code >= 400:
            self._raise_for(resp)
        return resp.json() if resp.text else {}

    @staticmethod
    def _raise_for(resp: requests.Response) -> None:
        try:
            err = resp.json().get('error', {})
            message = err.get('message', resp.text)
        except ValueError:
            message = resp.text
        low = message.lower()
        if resp.status_code == 429 or 'quota' in low:
            raise exceptions.QuotaExceededError(f'TPU API quota: {message}')
        if ('no more capacity' in low or 'stockout' in low or
                'resource_exhausted' in low or resp.status_code == 409 and
                'capacity' in low):
            raise exceptions.CapacityError(f'TPU capacity: {message}')
        if resp.status_code == 404:
            raise exceptions.ClusterDoesNotExist(message)
        if resp.status_code in (401, 403):
            raise exceptions.NoCloudAccessError(message)
        raise exceptions.ProvisionError(
            f'TPU API error {resp.status_code}: {message}')

    # -- nodes -----------------------------------------------------------
    def _node_url(self, zone: str, node_id: str) -> str:
        return (f'{TPU_API}/projects/{self.project}/locations/{zone}'
                f'/nodes/{node_id}')

    def create_node(self, zone: str, node_id: str, *,
                    accelerator_type: str,
                    runtime_version: str,
                    spot: bool = False,
                    labels: Optional[Dict[str, str]] = None,
                    startup_script: Optional[str] = None,
                    network: Optional[str] = None,
                    metadata: Optional[Dict[str, str]] = None,
                    data_disks: Optional[List[str]] = None,
                    tags: Optional[List[str]] = None
                    ) -> Dict[str, Any]:
        body: Dict[str, Any] = {
            'acceleratorType': accelerator_type,
            'runtimeVersion': runtime_version,
            'networkConfig': {'enableExternalIps': True},
            'labels': labels or {},
        }
        if tags:
            # Network tags: firewall rules target the slice's VMs by tag
            # (open_ports) instead of blanketing the whole VPC.
            body['tags'] = list(tags)
        if data_disks:
            # gcp-pd volumes: the TPU API only attaches disks at create.
            body['dataDisks'] = [
                {'sourceDisk': d if '/' in d else
                 f'projects/{self.project}/zones/{zone}/disks/{d}',
                 'mode': 'READ_WRITE'} for d in data_disks]
        if network:
            body['networkConfig']['network'] = network
        if spot:
            body['schedulingConfig'] = {'spot': True}
        if metadata:
            body['metadata'] = dict(metadata)
        if startup_script:
            body.setdefault('metadata', {})['startup-script'] = (
                startup_script)
        url = (f'{TPU_API}/projects/{self.project}/locations/{zone}'
               f'/nodes?nodeId={node_id}')
        op = self._request('POST', url, body)
        return self.wait_operation(op)

    def get_node(self, zone: str, node_id: str) -> Dict[str, Any]:
        return self._request('GET', self._node_url(zone, node_id))

    def delete_node(self, zone: str, node_id: str) -> None:
        try:
            op = self._request('DELETE', self._node_url(zone, node_id))
        except exceptions.ClusterDoesNotExist:
            return
        self.wait_operation(op)

    def stop_node(self, zone: str, node_id: str) -> None:
        op = self._request('POST',
                           f'{self._node_url(zone, node_id)}:stop', {})
        self.wait_operation(op)

    def start_node(self, zone: str, node_id: str) -> None:
        op = self._request('POST',
                           f'{self._node_url(zone, node_id)}:start', {})
        self.wait_operation(op)

    def list_nodes(self, zone: str) -> List[Dict[str, Any]]:
        out = self._request(
            'GET',
            f'{TPU_API}/projects/{self.project}/locations/{zone}/nodes')
        return out.get('nodes', [])

    # -- operations (reference instance_utils.py:1234) -------------------
    def wait_operation(self, op: Dict[str, Any],
                       timeout: float = OPERATION_TIMEOUT) -> Dict[str, Any]:
        name = op.get('name')
        if name is None or op.get('done'):
            return op.get('response', op)
        deadline = time.time() + timeout
        while time.time() < deadline:
            cur = self._request('GET', f'{TPU_API}/{name}')
            if cur.get('done'):
                if 'error' in cur:
                    msg = cur['error'].get('message', str(cur['error']))
                    low = msg.lower()
                    if 'capacity' in low or 'stockout' in low:
                        raise exceptions.CapacityError(msg)
                    if 'quota' in low:
                        raise exceptions.QuotaExceededError(msg)
                    raise exceptions.ProvisionError(msg)
                return cur.get('response', cur)
            time.sleep(OPERATION_POLL_INTERVAL)
        raise exceptions.ProvisionTimeoutError(
            f'TPU operation {name} timed out after {timeout}s')


def default_project() -> str:
    """Project from env/ADC (mirrors gcp/instance.py _project)."""
    import os
    proj = (os.environ.get('GOOGLE_CLOUD_PROJECT') or
            os.environ.get('GCP_PROJECT'))
    if proj:
        return proj
    try:
        import google.auth
        _, proj = google.auth.default()
    except Exception as e:  # noqa: BLE001
        raise exceptions.NoCloudAccessError(
            f'Cannot determine GCP project: {e}') from e
    if not proj:
        raise exceptions.NoCloudAccessError(
            'No GCP project configured (set GOOGLE_CLOUD_PROJECT).')
    return proj


COMPUTE_API = 'https://compute.googleapis.com/compute/v1'


class _GceComputeClient(TpuApiClient):
    """Shared compute-API operation handling (TPU ops poll a different
    URL/shape than compute ops, so the inherited wait_operation cannot
    be reused). Subclasses pass the scope-specific operations URL."""

    @staticmethod
    def _check_compute_op_error(op: Dict[str, Any]) -> None:
        errors = (op.get('error') or {}).get('errors') or []
        if errors:
            msg = '; '.join(e.get('message', str(e)) for e in errors)
            if any('quota' in str(e).lower() for e in errors):
                raise exceptions.QuotaExceededError(msg)
            raise exceptions.ProvisionError(msg)

    def _wait_compute_op(self, op: Dict[str, Any], op_url_base: str,
                         timeout: float = 300.0) -> None:
        name = op.get('name')
        if name is None or op.get('status') == 'DONE':
            self._check_compute_op_error(op)
            return
        deadline = time.time() + timeout
        while time.time() < deadline:
            cur = self._request('GET', f'{op_url_base}/{name}')
            if cur.get('status') == 'DONE':
                self._check_compute_op_error(cur)
                return
            time.sleep(2.0)
        raise exceptions.ProvisionTimeoutError(
            f'Compute operation {name} timed out after {timeout}s')


class GceDiskClient(_GceComputeClient):
    """Persistent-disk ops for gcp-pd volumes (compute API; reuses the
    TPU client's auth/error mapping — reference provisions PDs through
    the same google-api plumbing)."""

    def _disk_url(self, zone: str, name: str = '') -> str:
        base = (f'{COMPUTE_API}/projects/{self.project}/zones/{zone}'
                f'/disks')
        return f'{base}/{name}' if name else base

    def _wait_zone_op(self, zone: str, op: Dict[str, Any],
                      timeout: float = 300.0) -> None:
        self._wait_compute_op(
            op, f'{COMPUTE_API}/projects/{self.project}/zones/{zone}'
            f'/operations', timeout)

    def create_disk(self, zone: str, name: str, size_gb: int, *,
                    disk_type: str = 'pd-balanced') -> Dict[str, Any]:
        body = {
            'name': name,
            'sizeGb': str(size_gb),
            'type': (f'projects/{self.project}/zones/{zone}/diskTypes/'
                     f'{disk_type}'),
            'labels': {'sky-tpu-volume': name},
        }
        try:
            op = self._request('POST', self._disk_url(zone), body)
        except exceptions.ProvisionError as e:
            if 'already exists' in str(e).lower():
                return self.get_disk(zone, name)
            raise
        # disks.insert is async; READY must mean the disk exists (an
        # async quota failure would otherwise surface at mount time).
        self._wait_zone_op(zone, op)
        return self.get_disk(zone, name)

    def get_disk(self, zone: str, name: str) -> Dict[str, Any]:
        return self._request('GET', self._disk_url(zone, name))

    def delete_disk(self, zone: str, name: str) -> None:
        try:
            op = self._request('DELETE', self._disk_url(zone, name))
            self._wait_zone_op(zone, op)
        except exceptions.ClusterDoesNotExist:
            pass   # already gone


class GceFirewallClient(_GceComputeClient):
    """VPC firewall-rule ops backing ``open_ports`` (compute API;
    reference sky/provision/gcp/config.py:424 _check_firewall_rules and
    the rule-create path around it — same rule shape: allow tcp:<ports>
    from 0.0.0.0/0 to the cluster's network tag)."""

    def _fw_url(self, name: str = '') -> str:
        base = f'{COMPUTE_API}/projects/{self.project}/global/firewalls'
        return f'{base}/{name}' if name else base

    def _wait_global_op(self, op: Dict[str, Any],
                        timeout: float = 300.0) -> None:
        self._wait_compute_op(
            op, f'{COMPUTE_API}/projects/{self.project}/global'
            f'/operations', timeout)

    def ensure_rule(self, name: str, *, network: str,
                    ports: List[str], target_tag: str,
                    source_ranges: Optional[List[str]] = None
                    ) -> Dict[str, Any]:
        """Create (or update, if the port set changed) an allow rule."""
        body = {
            'name': name,
            'network': (network if '/' in network else
                        f'projects/{self.project}/global/networks/'
                        f'{network}'),
            'direction': 'INGRESS',
            'allowed': [{'IPProtocol': 'tcp',
                         'ports': [str(p) for p in ports]}],
            'sourceRanges': source_ranges or ['0.0.0.0/0'],
            'targetTags': [target_tag],
        }
        try:
            existing = self._request('GET', self._fw_url(name))
        except exceptions.ClusterDoesNotExist:
            existing = None
        if existing is None:
            op = self._request('POST', self._fw_url(), body)
        else:
            # Only the rule's TCP entries count toward "already open"
            # (a udp:53 entry does not open tcp:53); non-tcp entries
            # ride along unchanged in the PATCH body.
            have = set()
            others = []
            for a in existing.get('allowed', []):
                if str(a.get('IPProtocol', '')).lower() == 'tcp':
                    if 'ports' not in a:
                        # GCP semantics: a tcp entry with no ports list
                        # allows ALL tcp ports — nothing to add, and a
                        # PATCH would narrow it.
                        return existing
                    have.update(str(p) for p in a.get('ports', []))
                else:
                    others.append(a)
            want = set(body['allowed'][0]['ports'])
            if want <= have:
                return existing
            # UNION with the live rule: a second open_ports call with a
            # different port list must not silently close earlier ports
            # (advisor finding, round 3).
            body['allowed'][0]['ports'] = sorted(
                have | want, key=lambda p: (len(p), p))
            body['allowed'].extend(others)
            op = self._request('PATCH', self._fw_url(name), body)
        self._wait_global_op(op)
        return body

    def delete_rule(self, name: str) -> None:
        try:
            op = self._request('DELETE', self._fw_url(name))
            self._wait_global_op(op)
        except exceptions.ClusterDoesNotExist:
            pass   # already gone
