"""Kubernetes (GKE TPU) provisioner.

Counterpart of the reference's largest provisioner
(sky/provision/kubernetes/instance.py, pod-based) redesigned TPU-first:
one StatefulSet = one TPU slice (see manifests.py). All cluster-API
access goes through ``kubectl`` with JSON output — the same dependency
surface as the reference's fallback paths, and trivially fakeable in
tests by putting a stub kubectl on PATH.

provider_config keys: ``context`` (kubeconfig context), ``namespace``
(default 'default'), ``image``, plus the generic zone injected by the
provisioner (ignored here — placement is the cluster's business).
"""
from __future__ import annotations

import json
import os
import secrets
import shlex
import subprocess
import time
from typing import Any, Dict, List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu import topology
from skypilot_tpu.provision.common import (ClusterInfo, HostInfo,
                                           ProvisionConfig)
from skypilot_tpu.provision.k8s import manifests
from skypilot_tpu.utils import tls

POD_WAIT_TIMEOUT = 600.0
_POLL = 2.0


def _pod_wait_timeout() -> float:
    """Resolved at call time so tests/operators can shorten the gang
    wait (a bound default argument froze the old module constant)."""
    env = os.environ.get('SKY_TPU_K8S_POD_WAIT_TIMEOUT')
    if env:
        try:
            return float(env)
        except ValueError:
            pass
    return POD_WAIT_TIMEOUT


def _kubectl(provider_config: Dict[str, Any], args: List[str],
             *, stdin: Optional[str] = None,
             timeout: float = 60.0) -> str:
    cmd = ['kubectl']
    if provider_config.get('context'):
        cmd += ['--context', provider_config['context']]
    cmd += ['-n', provider_config.get('namespace', 'default')]
    cmd += args
    try:
        # Always pass input (even empty) so the child's stdin is a pipe
        # that closes — an inherited stdin can block `kubectl apply -f -`
        # style reads forever.
        proc = subprocess.run(cmd, input=stdin or '',
                              capture_output=True,
                              text=True, timeout=timeout)
    except FileNotFoundError:
        raise exceptions.NoCloudAccessError(
            'kubectl not found on PATH (kubernetes cloud unavailable).'
        ) from None
    except subprocess.TimeoutExpired:
        raise exceptions.ProvisionError(
            f'kubectl timed out: {shlex.join(args)}') from None
    if proc.returncode != 0:
        err = proc.stderr.strip()
        low = err.lower()
        if 'insufficient' in low or 'exceeded quota' in low:
            raise exceptions.QuotaExceededError(f'[k8s] {err}')
        # NotFound only means "cluster gone" for reads/deletes of our
        # own objects; an apply failing with a missing namespace must
        # surface as a provisioning error, not ClusterDoesNotExist.
        if args and args[0] in ('get', 'delete') and \
                'notfound' in low.replace(' ', ''):
            raise exceptions.ClusterDoesNotExist(err)
        raise exceptions.ProvisionError(f'[k8s] kubectl failed: {err}')
    return proc.stdout


def _slice_obj_names(cluster_name: str, num_slices: int) -> List[str]:
    """StatefulSet/Service name per slice (bare name for one slice —
    back-compat; suffixed for multislice, same rule as the gcp
    provider's node names)."""
    if num_slices <= 1:
        return [cluster_name]
    return [f'{cluster_name}-s{j}' for j in range(num_slices)]


def run_instances(config: ProvisionConfig) -> ClusterInfo:
    # Per-cluster agent secret (see runtime/agent.py auth middleware).
    config.provider_config.setdefault('agent_token',
                                      secrets.token_hex(16))
    # Cluster TLS pair: agents serve HTTPS inside the pod network,
    # clients pin the fingerprint (utils/tls.py).
    tls.ensure_cluster_cert(config.provider_config,
                            config.cluster_name)
    tpu = topology.parse_tpu(config.tpu_slice) if config.tpu_slice \
        else None
    names = _slice_obj_names(config.cluster_name, config.num_slices)
    for j, obj_name in enumerate(names):
        manifest = manifests.render_slice(
            config.cluster_name, tpu,
            namespace=config.provider_config.get('namespace', 'default'),
            image=config.provider_config.get(
                'image', manifests.DEFAULT_IMAGE),
            labels=config.labels,
            use_spot=config.use_spot,
            pvc_volumes=config.data_disks,
            obj_name=obj_name, slice_id=j,
            num_slices=config.num_slices)
        _kubectl(config.provider_config, ['apply', '-f', '-'],
                 stdin=json.dumps(manifest))
    per_slice = tpu.num_hosts if tpu else 1
    _wait_pods_running(config.cluster_name, config.provider_config,
                       per_slice * max(config.num_slices, 1))
    info = get_cluster_info(config.cluster_name, config.provider_config)
    if info is None:
        raise exceptions.ProvisionError(
            f'[k8s] slice {config.cluster_name} vanished after apply')
    _bootstrap_agents(info, config)
    return info


def _wait_pods_running(cluster_name: str,
                       provider_config: Dict[str, Any],
                       num_hosts: int,
                       timeout: Optional[float] = None) -> None:
    """Gang wait: ALL pods of the slice must reach Running. Unschedulable
    TPU pods (no node pool with that topology) fail fast as capacity."""
    if timeout is None:
        timeout = _pod_wait_timeout()
    deadline = time.time() + timeout
    while time.time() < deadline:
        # Terminating pods (deletionTimestamp set, phase still Running)
        # from a just-deleted previous incarnation self-heal within the
        # grace period — they must neither satisfy the gang nor trip
        # the over-count fail-fast.
        pods = [p for p in _get_pods(cluster_name, provider_config)
                if not p.get('metadata', {}).get('deletionTimestamp')]
        phases = [p['status'].get('phase') for p in pods]
        if len(pods) == num_hosts and all(ph == 'Running'
                                          for ph in phases):
            return
        if len(pods) > num_hosts:
            # Over-count never self-heals within this wait (stale pods
            # from a previous size, a half-deleted StatefulSet, or a
            # mis-sized gang) — spinning the full timeout just hides it.
            raise exceptions.ProvisionError(
                f'[k8s] slice {cluster_name}: {len(pods)} pods found '
                f'but the gang expects {num_hosts}; stale pods from a '
                f'previous size or a conflicting StatefulSet?')
        for p in pods:
            name = p['metadata']['name']
            if p['status'].get('phase') in ('Failed', 'Succeeded'):
                raise exceptions.ProvisionError(
                    f'[k8s] pod {name} terminal phase '
                    f'{p["status"]["phase"]} during provisioning')
            for cond in p['status'].get('conditions', []) or []:
                if (cond.get('type') == 'PodScheduled' and
                        cond.get('status') == 'False' and
                        cond.get('reason') == 'Unschedulable'):
                    raise exceptions.CapacityError(
                        f'[k8s] {name} unschedulable: '
                        f'{cond.get("message", "")}')
            for cs in p['status'].get('containerStatuses', []) or []:
                waiting = (cs.get('state') or {}).get('waiting') or {}
                if waiting.get('reason') in (
                        'ErrImagePull', 'ImagePullBackOff',
                        'CreateContainerConfigError',
                        'CreateContainerError', 'CrashLoopBackOff'):
                    raise exceptions.ProvisionError(
                        f'[k8s] pod {name}: {waiting["reason"]}: '
                        f'{waiting.get("message", "")}')
        time.sleep(_POLL)
    raise exceptions.ProvisionTimeoutError(
        f'[k8s] slice {cluster_name}: pods not Running within '
        f'{timeout}s')


def _get_pods(cluster_name: str,
              provider_config: Dict[str, Any]) -> List[Dict[str, Any]]:
    out = _kubectl(provider_config, [
        'get', 'pods', '-l',
        f'{manifests.LABEL_CLUSTER}={cluster_name}', '-o', 'json'])
    return json.loads(out).get('items', [])


def _bootstrap_agents(info: ClusterInfo, config: ProvisionConfig) -> None:
    """Install + start the agent in every pod via kubectl exec (mirrors
    the TPU-VM path's per-host agent install). Slice-aware: each agent
    learns its (slice_id, global rank) so the distributed env wires
    MEGASCALE coordinates for multislice gangs (same contract as the
    gcp provider's _install_agents)."""
    host_ips = [h.internal_ip for h in info.hosts]
    num_slices = max(config.num_slices, 1)
    hosts_per_slice = len(info.hosts) // num_slices
    for rank, host in enumerate(info.hosts):
        pod = host.host_id
        agent_config = {
            'cluster_name': info.cluster_name,
            'mode': 'host',
            'auth_token': config.provider_config.get('agent_token'),
            'tls_cert_pem': config.provider_config.get('agent_tls_cert'),
            'tls_key_pem': config.provider_config.get('agent_tls_key'),
            'host_rank': rank,
            'host_ips': host_ips,
            'num_hosts': hosts_per_slice,
            'num_slices': num_slices,
            'slice_id': rank // hosts_per_slice,
            'tpu_slice': info.tpu_slice,
            'peer_agent_urls': [
                f'{tls.scheme_for(config.provider_config.get("agent_tls_cert"))}'
                f'://{ip}:{manifests.AGENT_PORT}'
                for i, ip in enumerate(host_ips) if i != rank
            ] if rank == 0 else [],
            'provider_config': {
                k: v for k, v in config.provider_config.items()
                if k in ('context', 'namespace')},
        }
        # Ship the LOCAL framework tree into the pod (kubectl cp; the ssh
        # provider rsyncs the same way) — pip would install a different
        # or missing package and its failure would be invisible behind
        # the backgrounded agent.
        import skypilot_tpu
        from skypilot_tpu.utils import command_runner
        runner = command_runner.KubectlCommandRunner(
            pod,
            namespace=config.provider_config.get('namespace', 'default'),
            context=config.provider_config.get('context'))
        pkg_root = os.path.dirname(os.path.abspath(
            skypilot_tpu.__file__))
        runner.rsync(pkg_root, '/opt/sky_tpu/cluster/skypilot_tpu')
        # Pidfile probe, NOT pgrep: the exec'd shell's own cmdline
        # contains the agent start text, so `pgrep -f <pattern> ||
        # start` SELF-MATCHES and the agent never starts (same bug the
        # fake-ssh multihost e2e caught in the ssh provider).
        script = (
            f"printf %s {shlex.quote(json.dumps(agent_config))} "
            '> /opt/sky_tpu/cluster/agent_config.json && '
            '(python3 -c "import aiohttp" 2>/dev/null || '
            'python3 -m pip install -q aiohttp) && '
            'AP="$(cat /opt/sky_tpu/agent.pid 2>/dev/null)"; '
            'if ! { kill -0 "$AP" 2>/dev/null && '
            'grep -q runtime.agent "/proc/$AP/cmdline" 2>/dev/null; }; '
            'then PYTHONPATH=/opt/sky_tpu/cluster '
            'nohup python3 -m skypilot_tpu.runtime.agent '
            '--cluster-dir /opt/sky_tpu/cluster --host 0.0.0.0 '
            f'--port {manifests.AGENT_PORT} '
            '>/opt/sky_tpu/agent.log 2>&1 & '
            'echo $! > /opt/sky_tpu/agent.pid; fi')
        runner.run(script, check=True, timeout=300.0)


def _cluster_sts(cluster_name: str,
                 provider_config: Dict[str, Any]) -> List[Dict[str, Any]]:
    """Every StatefulSet of this cluster (one per slice), by label.
    Accepts both list and single-object kubectl responses."""
    try:
        out = _kubectl(provider_config, [
            'get', 'statefulset', '-l',
            f'{manifests.LABEL_CLUSTER}={cluster_name}', '-o', 'json'])
        body = json.loads(out)
    except (exceptions.ClusterDoesNotExist, exceptions.ProvisionError,
            json.JSONDecodeError):
        return []
    items = body.get('items') if isinstance(body, dict) else None
    if items is None:
        items = [body] if body.get('metadata') else []
    for s in items:
        # Single-object responses (and older harnesses) may omit the
        # name; the bare cluster name is the pre-multislice convention.
        s.setdefault('metadata', {}).setdefault('name', cluster_name)
    return sorted(items, key=lambda s: s['metadata']['name'])


def stop_instances(cluster_name: str,
                   provider_config: Dict[str, Any]) -> None:
    # Pods hold TPU chips; "stop" scales the gang to zero, releasing the
    # slice(s) but keeping the StatefulSets/Services for a fast start.
    names = ([s['metadata']['name']
              for s in _cluster_sts(cluster_name, provider_config)]
             or [cluster_name])
    for name in names:
        _kubectl(provider_config, ['scale', 'statefulset', name,
                                   '--replicas', '0'])


def start_instances(cluster_name: str,
                    provider_config: Dict[str, Any]) -> ClusterInfo:
    stss = _cluster_sts(cluster_name, provider_config)
    if not stss:
        raise exceptions.ClusterDoesNotExist(cluster_name)
    total = 0
    for sts in stss:
        # Original gang size survives in the label we wrote.
        num = sts['metadata'].get('labels', {}).get('sky-tpu-num-hosts')
        if num is None:
            num = sts['spec'].get('replicas') or 1
        total += int(num)
        _kubectl(provider_config, ['scale', 'statefulset',
                                   sts['metadata']['name'],
                                   '--replicas', str(num)])
    _wait_pods_running(cluster_name, provider_config, total)
    info = get_cluster_info(cluster_name, provider_config)
    assert info is not None
    return info


def terminate_instances(cluster_name: str,
                        provider_config: Dict[str, Any]) -> None:
    names = ([s['metadata']['name']
              for s in _cluster_sts(cluster_name, provider_config)]
             or [cluster_name])
    try:
        for name in names:
            _kubectl(provider_config, ['delete', 'statefulset', name,
                                       '--ignore-not-found'])
            _kubectl(provider_config, ['delete', 'service', name,
                                       '--ignore-not-found'])
        _kubectl(provider_config, ['delete', 'service',
                                   f'{cluster_name}-ports',
                                   '--ignore-not-found'])
    except exceptions.ClusterDoesNotExist:
        pass


def wait_instances(cluster_name: str, provider_config: Dict[str, Any],
                   state: str = 'RUNNING') -> None:
    info = get_cluster_info(cluster_name, provider_config)
    if info is None:
        raise exceptions.ProvisionError(
            f'[k8s] slice {cluster_name} does not exist')
    bad = [h for h in info.hosts if h.state != state]
    if bad:
        raise exceptions.ProvisionError(
            f'[k8s] hosts not {state}: {[h.host_id for h in bad]}')


_PHASE_TO_STATE = {
    'Running': 'RUNNING',
    'Pending': 'STARTING',
    'Succeeded': 'TERMINATED',
    'Failed': 'TERMINATED',
    'Unknown': 'UNKNOWN',
}


def _expected_hosts(cluster_name: str,
                    provider_config: Dict[str, Any],
                    stss: Optional[List[Dict[str, Any]]] = None
                    ) -> Optional[int]:
    """The gang's CURRENT intended host count, summed over every slice
    StatefulSet.

    spec.replicas first (0 after a scale-to-zero stop — which must not
    read as a dead gang), the sky-tpu-num-hosts label as fallback.
    None = the StatefulSet(s) are gone (terminated cluster).
    ``stss``: pass a pre-fetched _cluster_sts result to skip the kubectl
    round trip (status-poll hot path)."""
    if stss is None:
        stss = _cluster_sts(cluster_name, provider_config)
    if not stss:
        # Selector queries may be unsupported by a minimal harness; fall
        # back to the bare-name read.
        try:
            out = _kubectl(provider_config, ['get', 'statefulset',
                                             cluster_name, '-o', 'json'])
            stss = [json.loads(out)]
        except (exceptions.ClusterDoesNotExist,
                exceptions.ProvisionError, json.JSONDecodeError):
            return None
    total = 0
    for sts in stss:
        replicas = sts.get('spec', {}).get('replicas')
        if replicas is not None:
            total += int(replicas)
            continue
        label = (sts.get('metadata', {}).get('labels', {})
                 .get('sky-tpu-num-hosts'))
        if label and str(label).isdigit():
            total += int(label)
        else:
            return None
    return total


def get_cluster_info(cluster_name: str,
                     provider_config: Dict[str, Any]
                     ) -> Optional[ClusterInfo]:
    try:
        pods = _get_pods(cluster_name, provider_config)
    except exceptions.ClusterDoesNotExist:
        return None
    if not pods:
        # Distinguish scaled-to-zero (sts exists, replicas 0) from a
        # fully reclaimed gang (replicas > 0 but every pod deleted at
        # once — e.g. an N-host spot slice losing all N): the latter
        # must read as TERMINATED hosts or the managed-jobs
        # provider-plane watch (all-RUNNING check over an EMPTY list)
        # would call a dead slice healthy.
        stss = _cluster_sts(cluster_name, provider_config)
        expected = _expected_hosts(cluster_name, provider_config,
                                   stss=stss)
        if expected is None:
            return None
        # Slice-aware synthesis: a fully reclaimed S>=2 gang must keep
        # its real shape (per-slice pod names, num_slices) — consumers
        # correlate host_ids to pods and read the gang topology here.
        sts_slices = max(len(stss), 1)
        if sts_slices <= 1:
            names = [f'{cluster_name}-{i}' for i in range(expected)]
        else:
            per = expected // sts_slices
            names = [f'{s["metadata"]["name"]}-{i}'
                     for s in stss for i in range(per)]
        hosts: List[HostInfo] = [
            HostInfo(host_id=n, internal_ip='',
                     external_ip=None, state='TERMINATED',
                     agent_url=None)
            for n in names
        ]
        tpu_slice = None
    else:
        # (slice, ordinal) sort: lexicographic puts '-10' before '-2'
        # and scrambles host ranks on 10+-host slices; multislice pods
        # ('<cluster>-s<j>-<i>') must group by slice first so global
        # host_rank // hosts_per_slice recovers the slice id.
        def _ordinal(p):
            name = p['metadata']['name']
            labels = p.get('metadata', {}).get('labels', {})
            s = labels.get('sky-tpu-slice', '0')
            tail = name.rsplit('-', 1)[-1]
            return (int(s) if str(s).isdigit() else 0,
                    int(tail) if tail.isdigit() else 0)
        pods.sort(key=_ordinal)
        hosts = []
        scheme = tls.scheme_for(provider_config.get('agent_tls_cert'))
        for i, p in enumerate(pods):
            ip = p['status'].get('podIP', '')
            hosts.append(HostInfo(
                host_id=p['metadata']['name'],
                internal_ip=ip,
                external_ip=None,
                state=_PHASE_TO_STATE.get(
                    p['status'].get('phase', 'Unknown'), 'UNKNOWN'),
                agent_url=(f'{scheme}://{ip}:{manifests.AGENT_PORT}'
                           if ip else None)))
        # A reclaimed spot pod is DELETED, not Failed — with only live
        # pods listed, a 3/4 gang would read as all-RUNNING and the
        # managed-jobs provider-plane watch would never fire. Compare
        # against the gang size (the sky-tpu-num-hosts label rides on
        # every pod — no extra kubectl round trip) and surface missing
        # ordinals as TERMINATED hosts.
        labels0 = pods[0].get('metadata', {}).get('labels', {})
        per_slice = labels0.get('sky-tpu-num-hosts')
        n_slices_label = labels0.get('sky-tpu-num-slices')
        n_slices = (int(n_slices_label)
                    if n_slices_label and str(n_slices_label).isdigit()
                    else 1)
        # The num-hosts label is PER SLICE: a whole reclaimed slice in
        # an S>=2 gang would go unnoticed if compared against the
        # all-slice pod count (advisor finding, round 3).
        expected = (int(per_slice) * n_slices
                    if per_slice and str(per_slice).isdigit()
                    else _expected_hosts(cluster_name, provider_config))
        if expected is not None and len(hosts) < expected:
            present = {h.host_id for h in hosts}
            names = ([f'{cluster_name}-{i}' for i in range(expected)]
                     if n_slices <= 1 else
                     [f'{cluster_name}-s{j}-{i}'
                      for j in range(n_slices)
                      for i in range(expected // n_slices)])
            for pod_name in names:
                if pod_name not in present:
                    hosts.append(HostInfo(
                        host_id=pod_name, internal_ip='',
                        external_ip=None, state='TERMINATED',
                        agent_url=None))
        sel = (pods[0]['spec'].get('nodeSelector') or {})
        gke_acc = sel.get('cloud.google.com/gke-tpu-accelerator')
        topo = sel.get('cloud.google.com/gke-tpu-topology')
        tpu_slice = _slice_name_from_gke(gke_acc, topo)
    if pods:
        num_slices = 1
        ns_label = (pods[0].get('metadata', {}).get('labels', {})
                    .get('sky-tpu-num-slices'))
        if ns_label and str(ns_label).isdigit():
            num_slices = int(ns_label)
    else:
        num_slices = sts_slices
    return ClusterInfo(
        cluster_name=cluster_name,
        cloud='kubernetes',
        region=provider_config.get('context', 'in-cluster'),
        zone=provider_config.get('namespace', 'default'),
        hosts=hosts,
        tpu_slice=tpu_slice,
        num_slices=num_slices,
        instance_type=tpu_slice or 'pod',
        use_spot=False,
        cost_per_hour=0.0,
        provider_config={
            **{k: v for k, v in provider_config.items()
               if k in ('context', 'namespace', 'image', 'agent_token',
                        'agent_tls_cert', 'agent_tls_key')},
            'agent_cert_fingerprint': tls.fingerprint_of_pem(
                provider_config.get('agent_tls_cert'))})


def _slice_name_from_gke(gke_acc: Optional[str],
                         topo: Optional[str]) -> Optional[str]:
    if not gke_acc or not topo:
        return None
    gen_name = {v: k for k, v in
                manifests.GKE_TPU_ACCELERATOR.items()}.get(gke_acc)
    if gen_name is None:
        return None
    chips = 1
    for d in topo.split('x'):
        chips *= int(d)
    gen = topology.TPU_GENERATIONS[gen_name]
    suffix = (chips * gen.cores_per_chip if gen.suffix_counts_cores
              else chips)
    s = topology.parse_tpu(f'{gen_name}-{suffix}')
    return s.name if s is not None else f'{gen_name}-{suffix}'


def open_ports(cluster_name: str, ports,
               provider_config: Dict[str, Any]) -> None:
    """Expose ``ports`` via a Service over the slice's pods (reference's
    k8s provisioner uses Services the same way). Type LoadBalancer by
    default; ``ports_service_type: NodePort`` for clusters without an LB
    controller."""
    manifest = manifests.render_ports_service(
        cluster_name, [str(p) for p in ports],
        namespace=provider_config.get('namespace', 'default'),
        service_type=provider_config.get('ports_service_type',
                                         'LoadBalancer'))
    _kubectl(provider_config, ['apply', '-f', '-'],
             stdin=json.dumps(manifest))


def create_pvc(name: str, size_gb: int,
               provider_config: Dict[str, Any]) -> None:
    """Create the PVC backing a ``k8s-pvc`` volume (idempotent apply)."""
    manifest = manifests.render_pvc(
        name, size_gb,
        namespace=provider_config.get('namespace', 'default'),
        storage_class=provider_config.get('storage_class'))
    _kubectl(provider_config, ['apply', '-f', '-'],
             stdin=json.dumps(manifest))


def delete_pvc(name: str, provider_config: Dict[str, Any]) -> None:
    _kubectl(provider_config, ['delete', 'pvc', name,
                               '--ignore-not-found'])