"""Kubernetes (GKE TPU) provisioner.

Counterpart of the reference's largest provisioner
(sky/provision/kubernetes/instance.py, pod-based) redesigned TPU-first:
one StatefulSet = one TPU slice (see manifests.py). All cluster-API
access goes through ``kubectl`` with JSON output — the same dependency
surface as the reference's fallback paths, and trivially fakeable in
tests by putting a stub kubectl on PATH.

provider_config keys: ``context`` (kubeconfig context), ``namespace``
(default 'default'), ``image``, plus the generic zone injected by the
provisioner (ignored here — placement is the cluster's business).
"""
from __future__ import annotations

import json
import os
import shlex
import subprocess
import time
from typing import Any, Dict, List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu import topology
from skypilot_tpu.provision.common import (ClusterInfo, HostInfo,
                                           ProvisionConfig)
from skypilot_tpu.provision.k8s import manifests

POD_WAIT_TIMEOUT = 600.0
_POLL = 2.0


def _kubectl(provider_config: Dict[str, Any], args: List[str],
             *, stdin: Optional[str] = None,
             timeout: float = 60.0) -> str:
    cmd = ['kubectl']
    if provider_config.get('context'):
        cmd += ['--context', provider_config['context']]
    cmd += ['-n', provider_config.get('namespace', 'default')]
    cmd += args
    try:
        # Always pass input (even empty) so the child's stdin is a pipe
        # that closes — an inherited stdin can block `kubectl apply -f -`
        # style reads forever.
        proc = subprocess.run(cmd, input=stdin or '',
                              capture_output=True,
                              text=True, timeout=timeout)
    except FileNotFoundError:
        raise exceptions.NoCloudAccessError(
            'kubectl not found on PATH (kubernetes cloud unavailable).'
        ) from None
    except subprocess.TimeoutExpired:
        raise exceptions.ProvisionError(
            f'kubectl timed out: {shlex.join(args)}') from None
    if proc.returncode != 0:
        err = proc.stderr.strip()
        low = err.lower()
        if 'insufficient' in low or 'exceeded quota' in low:
            raise exceptions.QuotaExceededError(f'[k8s] {err}')
        # NotFound only means "cluster gone" for reads/deletes of our
        # own objects; an apply failing with a missing namespace must
        # surface as a provisioning error, not ClusterDoesNotExist.
        if args and args[0] in ('get', 'delete') and \
                'notfound' in low.replace(' ', ''):
            raise exceptions.ClusterDoesNotExist(err)
        raise exceptions.ProvisionError(f'[k8s] kubectl failed: {err}')
    return proc.stdout


def run_instances(config: ProvisionConfig) -> ClusterInfo:
    if config.num_slices > 1:
        raise exceptions.ProvisionError(
            'multislice (num_slices > 1) is supported on the gcp and '
            'local providers only; GKE multislice needs a JobSet path',
            retryable=False)
    tpu = topology.parse_tpu(config.tpu_slice) if config.tpu_slice \
        else None
    manifest = manifests.render_slice(
        config.cluster_name, tpu,
        namespace=config.provider_config.get('namespace', 'default'),
        image=config.provider_config.get(
            'image', manifests.DEFAULT_IMAGE),
        labels=config.labels,
        use_spot=config.use_spot,
        pvc_volumes=config.data_disks)
    _kubectl(config.provider_config, ['apply', '-f', '-'],
             stdin=json.dumps(manifest))
    _wait_pods_running(config.cluster_name, config.provider_config,
                       tpu.num_hosts if tpu else 1)
    info = get_cluster_info(config.cluster_name, config.provider_config)
    if info is None:
        raise exceptions.ProvisionError(
            f'[k8s] slice {config.cluster_name} vanished after apply')
    _bootstrap_agents(info, config)
    return info


def _wait_pods_running(cluster_name: str,
                       provider_config: Dict[str, Any],
                       num_hosts: int,
                       timeout: float = POD_WAIT_TIMEOUT) -> None:
    """Gang wait: ALL pods of the slice must reach Running. Unschedulable
    TPU pods (no node pool with that topology) fail fast as capacity."""
    deadline = time.time() + timeout
    while time.time() < deadline:
        pods = _get_pods(cluster_name, provider_config)
        phases = [p['status'].get('phase') for p in pods]
        if len(pods) == num_hosts and all(ph == 'Running'
                                          for ph in phases):
            return
        for p in pods:
            name = p['metadata']['name']
            if p['status'].get('phase') in ('Failed', 'Succeeded'):
                raise exceptions.ProvisionError(
                    f'[k8s] pod {name} terminal phase '
                    f'{p["status"]["phase"]} during provisioning')
            for cond in p['status'].get('conditions', []) or []:
                if (cond.get('type') == 'PodScheduled' and
                        cond.get('status') == 'False' and
                        cond.get('reason') == 'Unschedulable'):
                    raise exceptions.CapacityError(
                        f'[k8s] {name} unschedulable: '
                        f'{cond.get("message", "")}')
            for cs in p['status'].get('containerStatuses', []) or []:
                waiting = (cs.get('state') or {}).get('waiting') or {}
                if waiting.get('reason') in (
                        'ErrImagePull', 'ImagePullBackOff',
                        'CreateContainerConfigError',
                        'CreateContainerError', 'CrashLoopBackOff'):
                    raise exceptions.ProvisionError(
                        f'[k8s] pod {name}: {waiting["reason"]}: '
                        f'{waiting.get("message", "")}')
        time.sleep(_POLL)
    raise exceptions.ProvisionTimeoutError(
        f'[k8s] slice {cluster_name}: pods not Running within '
        f'{timeout}s')


def _get_pods(cluster_name: str,
              provider_config: Dict[str, Any]) -> List[Dict[str, Any]]:
    out = _kubectl(provider_config, [
        'get', 'pods', '-l',
        f'{manifests.LABEL_CLUSTER}={cluster_name}', '-o', 'json'])
    return json.loads(out).get('items', [])


def _bootstrap_agents(info: ClusterInfo, config: ProvisionConfig) -> None:
    """Install + start the agent in every pod via kubectl exec (mirrors
    the TPU-VM path's per-host agent install)."""
    host_ips = [h.internal_ip for h in info.hosts]
    for rank, host in enumerate(info.hosts):
        pod = f'{info.cluster_name}-{rank}'
        agent_config = {
            'cluster_name': info.cluster_name,
            'mode': 'host',
            'host_rank': rank,
            'host_ips': host_ips,
            'num_hosts': len(info.hosts),
            'tpu_slice': info.tpu_slice,
            'peer_agent_urls': [
                f'http://{ip}:{manifests.AGENT_PORT}'
                for i, ip in enumerate(host_ips) if i != rank
            ] if rank == 0 else [],
            'provider_config': {
                k: v for k, v in config.provider_config.items()
                if k in ('context', 'namespace')},
        }
        # Ship the LOCAL framework tree into the pod (kubectl cp; the ssh
        # provider rsyncs the same way) — pip would install a different
        # or missing package and its failure would be invisible behind
        # the backgrounded agent.
        import skypilot_tpu
        from skypilot_tpu.utils import command_runner
        runner = command_runner.KubectlCommandRunner(
            pod,
            namespace=config.provider_config.get('namespace', 'default'),
            context=config.provider_config.get('context'))
        pkg_root = os.path.dirname(os.path.abspath(
            skypilot_tpu.__file__))
        runner.rsync(pkg_root, '/opt/sky_tpu/cluster/skypilot_tpu')
        script = (
            f"printf %s {shlex.quote(json.dumps(agent_config))} "
            '> /opt/sky_tpu/cluster/agent_config.json && '
            '(python3 -c "import aiohttp" 2>/dev/null || '
            'python3 -m pip install -q aiohttp) && '
            "pgrep -f 'skypilot_tpu.runtime.agent' >/dev/null || "
            'PYTHONPATH=/opt/sky_tpu/cluster '
            'nohup python3 -m skypilot_tpu.runtime.agent '
            '--cluster-dir /opt/sky_tpu/cluster --host 0.0.0.0 '
            f'--port {manifests.AGENT_PORT} '
            '>/opt/sky_tpu/agent.log 2>&1 &')
        runner.run(script, check=True, timeout=300.0)


def stop_instances(cluster_name: str,
                   provider_config: Dict[str, Any]) -> None:
    # Pods hold TPU chips; "stop" scales the gang to zero, releasing the
    # slice but keeping the StatefulSet/Service for a fast start.
    _kubectl(provider_config, ['scale', 'statefulset', cluster_name,
                               '--replicas', '0'])


def start_instances(cluster_name: str,
                    provider_config: Dict[str, Any]) -> ClusterInfo:
    out = _kubectl(provider_config, ['get', 'statefulset', cluster_name,
                                     '-o', 'json'])
    sts = json.loads(out)
    # Original gang size survives in the selector-matched spec we wrote.
    num = sts['metadata']['labels'].get('sky-tpu-num-hosts')
    if num is None:
        # Pre-label manifests: best effort from current replicas.
        num = sts['spec'].get('replicas') or 1
    _kubectl(provider_config, ['scale', 'statefulset', cluster_name,
                               '--replicas', str(num)])
    _wait_pods_running(cluster_name, provider_config, int(num))
    info = get_cluster_info(cluster_name, provider_config)
    assert info is not None
    return info


def terminate_instances(cluster_name: str,
                        provider_config: Dict[str, Any]) -> None:
    try:
        _kubectl(provider_config, ['delete', 'statefulset', cluster_name,
                                   '--ignore-not-found'])
        _kubectl(provider_config, ['delete', 'service', cluster_name,
                                   '--ignore-not-found'])
        _kubectl(provider_config, ['delete', 'service',
                                   f'{cluster_name}-ports',
                                   '--ignore-not-found'])
    except exceptions.ClusterDoesNotExist:
        pass


def wait_instances(cluster_name: str, provider_config: Dict[str, Any],
                   state: str = 'RUNNING') -> None:
    info = get_cluster_info(cluster_name, provider_config)
    if info is None:
        raise exceptions.ProvisionError(
            f'[k8s] slice {cluster_name} does not exist')
    bad = [h for h in info.hosts if h.state != state]
    if bad:
        raise exceptions.ProvisionError(
            f'[k8s] hosts not {state}: {[h.host_id for h in bad]}')


_PHASE_TO_STATE = {
    'Running': 'RUNNING',
    'Pending': 'STARTING',
    'Succeeded': 'TERMINATED',
    'Failed': 'TERMINATED',
    'Unknown': 'UNKNOWN',
}


def _expected_hosts(cluster_name: str,
                    provider_config: Dict[str, Any]) -> Optional[int]:
    """The gang's CURRENT intended host count from the StatefulSet.

    spec.replicas first (0 after a scale-to-zero stop — which must not
    read as a dead gang), the sky-tpu-num-hosts label as fallback.
    None = the StatefulSet itself is gone (terminated cluster)."""
    try:
        out = _kubectl(provider_config, ['get', 'statefulset',
                                         cluster_name, '-o', 'json'])
        sts = json.loads(out)
    except (exceptions.ClusterDoesNotExist, exceptions.ProvisionError,
            json.JSONDecodeError):
        return None
    replicas = sts.get('spec', {}).get('replicas')
    if replicas is not None:
        return int(replicas)
    label = (sts.get('metadata', {}).get('labels', {})
             .get('sky-tpu-num-hosts'))
    return int(label) if label and str(label).isdigit() else None


def get_cluster_info(cluster_name: str,
                     provider_config: Dict[str, Any]
                     ) -> Optional[ClusterInfo]:
    try:
        pods = _get_pods(cluster_name, provider_config)
    except exceptions.ClusterDoesNotExist:
        return None
    if not pods:
        # Distinguish scaled-to-zero (sts exists, replicas 0) from a
        # fully reclaimed gang (replicas > 0 but every pod deleted at
        # once — e.g. an N-host spot slice losing all N): the latter
        # must read as TERMINATED hosts or the managed-jobs
        # provider-plane watch (all-RUNNING check over an EMPTY list)
        # would call a dead slice healthy.
        expected = _expected_hosts(cluster_name, provider_config)
        if expected is None:
            return None
        hosts: List[HostInfo] = [
            HostInfo(host_id=f'{cluster_name}-{i}', internal_ip='',
                     external_ip=None, state='TERMINATED',
                     agent_url=None)
            for i in range(expected)
        ]
        tpu_slice = None
    else:
        # Numeric ordinal sort: lexicographic puts '-10' before '-2'
        # and scrambles host ranks on 10+-host slices.
        def _ordinal(p):
            name = p['metadata']['name']
            tail = name.rsplit('-', 1)[-1]
            return int(tail) if tail.isdigit() else 0
        pods.sort(key=_ordinal)
        hosts = []
        for i, p in enumerate(pods):
            ip = p['status'].get('podIP', '')
            hosts.append(HostInfo(
                host_id=p['metadata']['name'],
                internal_ip=ip,
                external_ip=None,
                state=_PHASE_TO_STATE.get(
                    p['status'].get('phase', 'Unknown'), 'UNKNOWN'),
                agent_url=(f'http://{ip}:{manifests.AGENT_PORT}'
                           if ip else None)))
        # A reclaimed spot pod is DELETED, not Failed — with only live
        # pods listed, a 3/4 gang would read as all-RUNNING and the
        # managed-jobs provider-plane watch would never fire. Compare
        # against the gang size (the sky-tpu-num-hosts label rides on
        # every pod — no extra kubectl round trip) and surface missing
        # ordinals as TERMINATED hosts.
        label = (pods[0].get('metadata', {}).get('labels', {})
                 .get('sky-tpu-num-hosts'))
        expected = (int(label) if label and str(label).isdigit()
                    else _expected_hosts(cluster_name, provider_config))
        if expected is not None and len(hosts) < expected:
            present = {h.host_id for h in hosts}
            for i in range(expected):
                pod_name = f'{cluster_name}-{i}'
                if pod_name not in present:
                    hosts.append(HostInfo(
                        host_id=pod_name, internal_ip='',
                        external_ip=None, state='TERMINATED',
                        agent_url=None))
        sel = (pods[0]['spec'].get('nodeSelector') or {})
        gke_acc = sel.get('cloud.google.com/gke-tpu-accelerator')
        topo = sel.get('cloud.google.com/gke-tpu-topology')
        tpu_slice = _slice_name_from_gke(gke_acc, topo)
    return ClusterInfo(
        cluster_name=cluster_name,
        cloud='kubernetes',
        region=provider_config.get('context', 'in-cluster'),
        zone=provider_config.get('namespace', 'default'),
        hosts=hosts,
        tpu_slice=tpu_slice,
        instance_type=tpu_slice or 'pod',
        use_spot=False,
        cost_per_hour=0.0,
        provider_config={k: v for k, v in provider_config.items()
                         if k in ('context', 'namespace', 'image')})


def _slice_name_from_gke(gke_acc: Optional[str],
                         topo: Optional[str]) -> Optional[str]:
    if not gke_acc or not topo:
        return None
    gen_name = {v: k for k, v in
                manifests.GKE_TPU_ACCELERATOR.items()}.get(gke_acc)
    if gen_name is None:
        return None
    chips = 1
    for d in topo.split('x'):
        chips *= int(d)
    gen = topology.TPU_GENERATIONS[gen_name]
    suffix = (chips * gen.cores_per_chip if gen.suffix_counts_cores
              else chips)
    s = topology.parse_tpu(f'{gen_name}-{suffix}')
    return s.name if s is not None else f'{gen_name}-{suffix}'


def open_ports(cluster_name: str, ports,
               provider_config: Dict[str, Any]) -> None:
    """Expose ``ports`` via a Service over the slice's pods (reference's
    k8s provisioner uses Services the same way). Type LoadBalancer by
    default; ``ports_service_type: NodePort`` for clusters without an LB
    controller."""
    manifest = manifests.render_ports_service(
        cluster_name, [str(p) for p in ports],
        namespace=provider_config.get('namespace', 'default'),
        service_type=provider_config.get('ports_service_type',
                                         'LoadBalancer'))
    _kubectl(provider_config, ['apply', '-f', '-'],
             stdin=json.dumps(manifest))


def create_pvc(name: str, size_gb: int,
               provider_config: Dict[str, Any]) -> None:
    """Create the PVC backing a ``k8s-pvc`` volume (idempotent apply)."""
    manifest = manifests.render_pvc(
        name, size_gb,
        namespace=provider_config.get('namespace', 'default'),
        storage_class=provider_config.get('storage_class'))
    _kubectl(provider_config, ['apply', '-f', '-'],
             stdin=json.dumps(manifest))


def delete_pvc(name: str, provider_config: Dict[str, Any]) -> None:
    _kubectl(provider_config, ['delete', 'pvc', name,
                               '--ignore-not-found'])