"""Kubernetes manifest rendering for TPU slices on GKE.

The reference's largest provisioner is kubernetes
(sky/provision/kubernetes/, pod-based with jinja templates). The
TPU-native shape is different and simpler: a multi-host TPU slice on GKE
is a *StatefulSet with one pod per TPU-VM host* plus a headless Service
— GKE's TPU webhook injects TPU_WORKER_ID/TPU_WORKER_HOSTNAMES from the
pod ordinal when the pods carry the TPU nodeSelectors, which is exactly
the gang identity the agent needs.

GKE nodeSelector mapping (public GKE docs' accelerator names):
    v4  -> tpu-v4-podslice        v5e -> tpu-v5-lite-podslice
    v5p -> tpu-v5p-slice          v6e -> tpu-v6e-slice
"""
from __future__ import annotations

import os
from typing import Any, Dict, List, Optional

from skypilot_tpu import topology

GKE_TPU_ACCELERATOR = {
    'v4': 'tpu-v4-podslice',
    'v5e': 'tpu-v5-lite-podslice',
    'v5p': 'tpu-v5p-slice',
    'v6e': 'tpu-v6e-slice',
}

LABEL_CLUSTER = 'sky-tpu-cluster'
AGENT_PORT = 46590
DEFAULT_IMAGE = 'python:3.11-slim'


def render_slice(cluster_name: str,
                 tpu: Optional[topology.TpuSlice],
                 *,
                 namespace: str = 'default',
                 image: str = DEFAULT_IMAGE,
                 cpu: str = '4',
                 memory: str = '16Gi',
                 labels: Optional[Dict[str, str]] = None,
                 use_spot: bool = False,
                 pvc_volumes: Optional[List[str]] = None,
                 obj_name: Optional[str] = None,
                 slice_id: int = 0,
                 num_slices: int = 1
                 ) -> Dict[str, Any]:
    """Headless Service + StatefulSet for one slice (or one CPU pod when
    tpu is None). Returned as a kubectl-applyable List manifest.

    Multislice (GKE): one render per slice with ``obj_name``
    '<cluster>-s<j>'; every object still carries the CLUSTER label so
    list/terminate selectors cover the whole gang, plus slice labels the
    agents use for MEGASCALE wiring."""
    obj_name = obj_name or cluster_name
    num_hosts = tpu.num_hosts if tpu else 1
    # The gang size survives scale-to-zero stops via this label (start
    # reads it back to restore the full slice).
    meta_labels = {LABEL_CLUSTER: cluster_name,
                   'sky-tpu-num-hosts': str(num_hosts),
                   'sky-tpu-slice': str(slice_id),
                   'sky-tpu-num-slices': str(num_slices),
                   **(labels or {})}
    container: Dict[str, Any] = {
        'name': 'sky-host',
        'image': image,
        'command': ['/bin/bash', '-c'],
        # The agent is installed+started by the provisioner's bootstrap
        # exec (mirrors the TPU-VM path); the pod just stays alive.
        'args': ['sleep infinity'],
        'ports': [{'containerPort': AGENT_PORT, 'name': 'sky-agent'}],
        'resources': {'requests': {'cpu': cpu, 'memory': memory},
                      'limits': {}},
        'env': [
            {'name': 'SKY_TPU_CLUSTER', 'value': cluster_name},
            # Rootless FUSE: the shim (fuse_proxy) reads this to reach
            # the privileged fusermount-server DaemonSet's socket on the
            # shared hostPath (render_fuse_proxy_daemonset).
            {'name': 'SKY_TPU_FUSE_PROXY_SOCK',
             'value': '/var/run/fusermount/proxy.sock'},
        ],
        'volumeMounts': [{'name': 'fusermount-shared',
                          'mountPath': '/var/run/fusermount'}],
    }
    pod_spec: Dict[str, Any] = {
        'containers': [container],
        # Gang semantics: a slice pod that dies must come back on the
        # same slice; Never lets the controller recreate it instead of
        # restarting in place with stale TPU state.
        'restartPolicy': 'Always',
        'subdomain': obj_name,
        'volumes': [{'name': 'fusermount-shared',
                     'hostPath': {'path': '/var/run/fusermount',
                                  'type': 'DirectoryOrCreate'}}],
    }
    if tpu is not None:
        chips = tpu.chips_per_host
        container['resources']['requests']['google.com/tpu'] = str(chips)
        container['resources']['limits']['google.com/tpu'] = str(chips)
        pod_spec['nodeSelector'] = {
            'cloud.google.com/gke-tpu-accelerator':
                GKE_TPU_ACCELERATOR[tpu.generation],
            'cloud.google.com/gke-tpu-topology': tpu.topology_str,
        }
    if use_spot:
        # GKE spot node pools: schedule onto spot nodes and tolerate
        # their taint (the slice then rides spot pricing; preemption
        # surfaces as pod deletion, which the managed-jobs dual-plane
        # watch already treats as a dead gang).
        pod_spec.setdefault('nodeSelector', {})[
            'cloud.google.com/gke-spot'] = 'true'
        pod_spec.setdefault('tolerations', []).append({
            'key': 'cloud.google.com/gke-spot',
            'operator': 'Equal',
            'value': 'true',
            'effect': 'NoSchedule',
        })
    for vol_name in pvc_volumes or []:
        # PVC-backed volumes mount at a fixed in-pod path; the volume
        # mount step symlinks the task's requested path onto it.
        container['volumeMounts'].append(
            {'name': f'vol-{vol_name}', 'mountPath': f'/mnt/{vol_name}'})
        pod_spec['volumes'].append(
            {'name': f'vol-{vol_name}',
             'persistentVolumeClaim': {'claimName': vol_name}})
    # Per-slice pod identity: the Service/StatefulSet selectors include
    # the slice label, so multislice gangs don't cross-adopt pods.
    slice_selector = {LABEL_CLUSTER: cluster_name,
                      'sky-tpu-slice': str(slice_id)}
    service = {
        'apiVersion': 'v1',
        'kind': 'Service',
        'metadata': {'name': obj_name, 'namespace': namespace,
                     'labels': meta_labels},
        'spec': {
            'clusterIP': 'None',       # headless: stable per-pod DNS
            'selector': slice_selector,
            'ports': [{'port': AGENT_PORT, 'name': 'sky-agent'}],
        },
    }
    statefulset = {
        'apiVersion': 'apps/v1',
        'kind': 'StatefulSet',
        'metadata': {'name': obj_name, 'namespace': namespace,
                     'labels': meta_labels},
        'spec': {
            'serviceName': obj_name,
            'replicas': num_hosts,
            # All-or-nothing gang: pods start in parallel, not ordinal
            # order — host 7 must not wait for host 0's readiness.
            'podManagementPolicy': 'Parallel',
            'selector': {'matchLabels': slice_selector},
            'template': {
                'metadata': {'labels': meta_labels},
                'spec': pod_spec,
            },
        },
    }
    return {'apiVersion': 'v1', 'kind': 'List',
            'items': [service, statefulset]}


def _fuse_proxy_source() -> str:
    """The native fuse_proxy.cc source, shipped to the DaemonSet via a
    ConfigMap so the manifest is self-contained (the default image has
    no framework files)."""
    import skypilot_tpu
    candidates = [
        os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(
            skypilot_tpu.__file__))), 'native', 'fuse_proxy.cc'),
    ]
    for path in candidates:
        if os.path.exists(path):
            with open(path, encoding='utf-8') as f:
                return f.read()
    raise FileNotFoundError(
        'native/fuse_proxy.cc not found next to the package')


def render_ports_service(cluster_name: str, ports: List[str], *,
                         namespace: str = 'default',
                         service_type: str = 'LoadBalancer'
                         ) -> Dict[str, Any]:
    """Service exposing ``ports`` on the slice's pods (open_ports;
    reference's k8s provisioner exposes ports via Services). Default
    LoadBalancer for an external IP; set
    ``provider_config.ports_service_type: NodePort`` on clusters whose
    LB controller is absent."""
    return {
        'apiVersion': 'v1',
        'kind': 'Service',
        'metadata': {'name': f'{cluster_name}-ports',
                     'namespace': namespace,
                     'labels': {LABEL_CLUSTER: cluster_name}},
        'spec': {
            'type': service_type,
            'selector': {LABEL_CLUSTER: cluster_name},
            'ports': [{'port': int(p), 'targetPort': int(p),
                       'name': f'port-{p}'} for p in ports],
        },
    }


def render_pvc(name: str, size_gb: int, *,
               namespace: str = 'default',
               storage_class: Optional[str] = None,
               access_mode: str = 'ReadWriteOnce') -> Dict[str, Any]:
    """PersistentVolumeClaim backing a ``k8s-pvc`` volume."""
    spec: Dict[str, Any] = {
        'accessModes': [access_mode],
        'resources': {'requests': {'storage': f'{size_gb}Gi'}},
    }
    if storage_class is not None:
        spec['storageClassName'] = storage_class
    return {
        'apiVersion': 'v1',
        'kind': 'PersistentVolumeClaim',
        'metadata': {'name': name, 'namespace': namespace,
                     'labels': {'sky-tpu-volume': name}},
        'spec': spec,
    }


def render_fuse_proxy_daemonset(namespace: str = 'kube-system',
                                image: str = DEFAULT_IMAGE
                                ) -> Dict[str, Any]:
    """Privileged fusermount-server DaemonSet + source ConfigMap
    (reference addons/fuse-proxy's example manifest): shares
    /var/run/fusermount with workload pods; pods' containers mask
    `fusermount` with the shim personality of the same native binary."""
    shared = {'name': 'fusermount-shared',
              'hostPath': {'path': '/var/run/fusermount',
                           'type': 'DirectoryOrCreate'}}
    src_volume = {'name': 'fuse-proxy-src',
                  'configMap': {'name': 'sky-tpu-fuse-proxy-src'}}
    configmap = {
        'apiVersion': 'v1',
        'kind': 'ConfigMap',
        'metadata': {'name': 'sky-tpu-fuse-proxy-src',
                     'namespace': namespace},
        'data': {'fuse_proxy.cc': _fuse_proxy_source()},
    }
    daemonset = {
        'apiVersion': 'apps/v1',
        'kind': 'DaemonSet',
        'metadata': {'name': 'sky-tpu-fusermount-server',
                     'namespace': namespace,
                     'labels': {'app': 'sky-tpu-fusermount-server'}},
        'spec': {
            'selector': {'matchLabels':
                         {'app': 'sky-tpu-fusermount-server'}},
            'template': {
                'metadata': {'labels':
                             {'app': 'sky-tpu-fusermount-server'}},
                'spec': {
                    'hostPID': True,
                    # GKE taints TPU nodes (google.com/tpu:NoSchedule);
                    # workload pods tolerate it implicitly via their TPU
                    # resource request, the DaemonSet must do so
                    # explicitly or it never lands where mounts happen.
                    'tolerations': [
                        {'key': 'google.com/tpu', 'operator': 'Exists',
                         'effect': 'NoSchedule'}],
                    'containers': [{
                        'name': 'server',
                        'image': image,
                        'securityContext': {'privileged': True},
                        'command': ['/bin/bash', '-c'],
                        'args': [
                            'apt-get update -qq && '
                            'apt-get install -y -qq fuse3 g++ && '
                            'g++ -O2 -std=c++17 -o /usr/local/bin/'
                            'fuse_proxy /opt/native/fuse_proxy.cc && '
                            '/usr/local/bin/fuse_proxy server '
                            '--socket /var/run/fusermount/proxy.sock'],
                        'volumeMounts': [
                            {'name': 'fusermount-shared',
                             'mountPath': '/var/run/fusermount'},
                            {'name': 'fuse-proxy-src',
                             'mountPath': '/opt/native'}],
                    }],
                    'volumes': [shared, src_volume],
                },
            },
        },
    }
    return {'apiVersion': 'v1', 'kind': 'List',
            'items': [configmap, daemonset]}
