"""Provider-routed provisioning API.

Counterpart of the reference's ``sky/provision/__init__.py`` (function
registry dispatched by cloud name via ``@_route_to_cloud_impl``, :48, ops
at :81-345). Each provider module exposes the same function set; dispatch
is by module lookup so adding a cloud is dropping in a module.

Provider contract (all take/return plain data, no cloud SDK types leak):
    run_instances(config: ProvisionConfig) -> ClusterInfo
    stop_instances(cluster_name, provider_config) -> None
    terminate_instances(cluster_name, provider_config) -> None
    wait_instances(cluster_name, provider_config, state) -> None
    get_cluster_info(cluster_name, provider_config) -> Optional[ClusterInfo]
    open_ports(cluster_name, ports, provider_config) -> None
"""
from __future__ import annotations

import importlib
from typing import Any, Dict, Optional

from skypilot_tpu import exceptions
from skypilot_tpu.provision.common import ClusterInfo, ProvisionConfig
from skypilot_tpu.utils import failpoints

_PROVIDERS = {
    'local': 'skypilot_tpu.provision.local.instance',
    'gcp': 'skypilot_tpu.provision.gcp.instance',
    'ssh': 'skypilot_tpu.provision.ssh.instance',
    'kubernetes': 'skypilot_tpu.provision.k8s.instance',
    'slurm': 'skypilot_tpu.provision.slurm.instance',
}


def _impl(cloud: str):
    if cloud not in _PROVIDERS:
        raise exceptions.ProvisionError(
            f'No provisioner for cloud {cloud!r}', retryable=False)
    return importlib.import_module(_PROVIDERS[cloud])


def run_instances(cloud: str, config: ProvisionConfig) -> ClusterInfo:
    return _impl(cloud).run_instances(config)


def stop_instances(cloud: str, cluster_name: str,
                   provider_config: Dict[str, Any]) -> None:
    return _impl(cloud).stop_instances(cluster_name, provider_config)


def terminate_instances(cloud: str, cluster_name: str,
                        provider_config: Dict[str, Any]) -> None:
    # Chaos seam: teardown paths are all best-effort by contract, so an
    # injected error here verifies no caller lets a failed terminate
    # wedge recovery (the cleanup-is-never-on-the-critical-path rule).
    failpoints.hit('provision.terminate')
    return _impl(cloud).terminate_instances(cluster_name, provider_config)


def wait_instances(cloud: str, cluster_name: str,
                   provider_config: Dict[str, Any],
                   state: str = 'RUNNING') -> None:
    return _impl(cloud).wait_instances(cluster_name, provider_config, state)


def get_cluster_info(cloud: str, cluster_name: str,
                     provider_config: Dict[str, Any]
                     ) -> Optional[ClusterInfo]:
    return _impl(cloud).get_cluster_info(cluster_name, provider_config)


def probe_cluster_running(info: ClusterInfo) -> bool:
    """Provider-plane liveness: every slice host RUNNING.

    The one preemption-detection predicate (SURVEY.md "hard parts":
    no NCCL-timeout signal on TPU — the provider's view of the slice is
    authoritative). A probe *error* is treated as alive: a flaky
    control-plane call must not trigger recovery. Shared by the managed-
    jobs controller, the serve replica manager, and the pool strategy.
    """
    try:
        live = get_cluster_info(info.cloud, info.cluster_name,
                                info.provider_config)
    except Exception:  # noqa: BLE001 — flaky probe ≠ dead slice
        return True
    if live is None:
        return False
    return all(h.state == 'RUNNING' for h in live.hosts)


def probe_preemption_notice(info: ClusterInfo) -> bool:
    """Advance warning that the provider is about to reclaim the slice
    (GCP TPU maintenance/preemption events expose one; most providers
    don't). The serve replica manager turns a notice into a graceful
    drain — the spot reclaim becomes a planned handoff instead of a
    mid-stream corpse. Providers without the signal report False, and a
    probe ERROR is never a notice (a flaky control-plane call must not
    trigger churn). The `jobs.provider.preemption_notice` failpoint
    injects a notice for the chaos suite."""
    try:
        failpoints.hit('jobs.provider.preemption_notice')
    except failpoints.FailpointError:
        return True
    try:
        probe = getattr(_impl(info.cloud), 'probe_preemption_notice',
                        None)
        if probe is None:
            return False
        return bool(probe(info.cluster_name, info.provider_config))
    except Exception:  # noqa: BLE001 — flaky probe ≠ notice
        return False


def open_ports(cloud: str, cluster_name: str, ports,
               provider_config: Dict[str, Any]) -> None:
    return _impl(cloud).open_ports(cluster_name, ports, provider_config)


def start_instances(cloud: str, cluster_name: str,
                    provider_config: Dict[str, Any]) -> ClusterInfo:
    """Restart a STOPPED cluster."""
    return _impl(cloud).start_instances(cluster_name, provider_config)
