"""Slurm provider package (reference sky/clouds/slurm.py +
sky/skylet/executor/slurm.py, redesigned agent-first)."""
