"""Slurm provider: a cluster is one sbatch allocation running agents.

Counterpart of the reference's Slurm support (reference
sky/clouds/slurm.py as a cloud + sky/skylet/executor/slurm.py as an
alternative on-cluster executor). The TPU-native redesign keeps ONE
runtime everywhere instead of a second executor: ``run_instances``
submits an sbatch job whose only payload is `srun` starting the standard
on-host agent on every allocated node (host mode, head = node 0), so
jobs/logs/autostop/serve all work unchanged on Slurm — the allocation is
just another way to obtain a gang of hosts.

Assumptions: this process runs where Slurm's client tools work (a login
node — the usual deployment for an on-prem API server), and
``$SKY_TPU_HOME`` lives on a filesystem shared with the compute nodes
(standard on-prem setup) — agents read their config from it and the
backend syncs workdirs through it. Config:

    slurm:
      partition: tpu        # optional
      account: myacct       # optional
      time_limit: 7-00:00:00  # optional, sbatch -t

Lifecycle mapping: stop = scancel (release the allocation, keep
metadata), start = resubmit, terminate = scancel + forget. Offline tests
drive the full provider against stub sbatch/squeue/scontrol binaries
(tests/unit_tests/test_slurm_provisioner.py), mirroring the fake-cloud
test strategy.
"""
from __future__ import annotations

import json
import os
import secrets
import shutil
import subprocess
import time
from typing import Any, Dict, List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu.provision.common import (ClusterInfo, HostInfo,
                                           ProvisionConfig)
from skypilot_tpu.utils import common
from skypilot_tpu.utils import tls

AGENT_PORT = 46590
SUBMIT_TIMEOUT_S = 30.0


def _cluster_dir(cluster_name: str) -> str:
    return os.path.join(common.clusters_dir(), cluster_name)


def _run(cmd: List[str], timeout: float = SUBMIT_TIMEOUT_S) -> str:
    if shutil.which(cmd[0]) is None:
        raise exceptions.NoCloudAccessError(
            f'{cmd[0]!r} not found on PATH — the Slurm provider must run '
            f'where Slurm client tools are installed (a login node).')
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=timeout)
    except subprocess.TimeoutExpired as e:
        # Must be a SkyTpuError: a hung slurmctld has to ride the
        # failover/error paths, not escape as a raw traceback.
        raise exceptions.ProvisionError(
            f'[slurm] {cmd[0]} timed out after {timeout}s '
            f'(slurmctld unresponsive?)', retryable=True) from e
    if proc.returncode != 0:
        raise exceptions.ProvisionError(
            f'[slurm] {" ".join(cmd[:2])} failed: '
            f'{proc.stderr.strip() or proc.stdout.strip()}',
            retryable=False)
    return proc.stdout


def _meta(cdir: str) -> Optional[Dict[str, Any]]:
    p = os.path.join(cdir, 'meta.json')
    if not os.path.exists(p):
        return None
    with open(p, encoding='utf-8') as f:
        return json.load(f)


def _write_meta(cdir: str, meta: Dict[str, Any]) -> None:
    tmp = os.path.join(cdir, 'meta.json.tmp')
    with open(tmp, 'w', encoding='utf-8') as f:
        json.dump(meta, f)
    os.replace(tmp, os.path.join(cdir, 'meta.json'))


def _node_script(cdir: str, cluster_name: str,
                 tpu_slice: Optional[str], token: str,
                 cert_pem: Optional[str] = None,
                 key_pem: Optional[str] = None) -> str:
    """The per-node srun payload: derive rank/hosts from the Slurm env,
    write the agent config, run the agent in the foreground (the srun
    task's lifetime IS the allocation's)."""
    from skypilot_tpu.utils import tls as tls_lib
    scheme = tls_lib.scheme_for(cert_pem)
    return f"""#!/bin/bash
set -e
RANK=${{SLURM_NODEID:?}}
NODE_DIR={cdir}/host$RANK
mkdir -p "$NODE_DIR"
HOSTS=$(scontrol show hostnames "$SLURM_JOB_NODELIST")
python3 - "$RANK" "$NODE_DIR" <<'PYEOF'
import json, os, sys
rank, node_dir = int(sys.argv[1]), sys.argv[2]
hosts = os.environ['SKY_TPU_SLURM_HOSTS'].split()
cfg = {{
    'cluster_name': {cluster_name!r},
    'mode': 'host',
    'host_rank': rank,
    'host_ips': hosts,
    'num_hosts': len(hosts),
    'tpu_slice': {tpu_slice!r},
    'auth_token': {token!r},
    'tls_cert_pem': {cert_pem!r},
    'tls_key_pem': {key_pem!r},
    'peer_agent_urls': [f'{scheme}://{{h}}:{AGENT_PORT}'
                        for i, h in enumerate(hosts) if i != rank]
                       if rank == 0 else [],
}}
with open(os.path.join(node_dir, 'agent_config.json'), 'w') as f:
    json.dump(cfg, f)
PYEOF
exec env SKY_TPU_SLURM_HOSTS="$HOSTS" python3 -m \\
    skypilot_tpu.runtime.agent --cluster-dir "$NODE_DIR" \\
    --host 0.0.0.0 --port {AGENT_PORT}
"""


def _sbatch_script(config: ProvisionConfig, cdir: str) -> str:
    pc = config.provider_config
    lines = ['#!/bin/bash',
             f'#SBATCH --job-name=sky-tpu-{config.cluster_name}',
             f'#SBATCH --nodes={config.num_hosts}',
             '#SBATCH --ntasks-per-node=1',
             f'#SBATCH --output={cdir}/slurm.log']
    if pc.get('partition'):
        lines.append(f'#SBATCH --partition={pc["partition"]}')
    if pc.get('account'):
        lines.append(f'#SBATCH --account={pc["account"]}')
    if pc.get('time_limit'):
        lines.append(f'#SBATCH --time={pc["time_limit"]}')
    lines += [
        'export SKY_TPU_SLURM_HOSTS="$(scontrol show hostnames '
        '"$SLURM_JOB_NODELIST")"',
        f'srun --ntasks-per-node=1 bash {cdir}/node_start.sh',
    ]
    return '\n'.join(lines) + '\n'


def _submit(config: ProvisionConfig, cdir: str) -> str:
    # Per-cluster agent secret (see runtime/agent.py auth middleware);
    # rides meta['provider_config'] so get_cluster_info preserves it.
    config.provider_config.setdefault('agent_token',
                                      secrets.token_hex(16))
    # Cluster TLS pair (utils/tls.py) — generated with the token,
    # delivered via the node-start script on the shared filesystem.
    tls.ensure_cluster_cert(config.provider_config,
                            config.cluster_name)
    with open(os.path.join(cdir, 'node_start.sh'), 'w',
              encoding='utf-8') as f:
        f.write(_node_script(cdir, config.cluster_name, config.tpu_slice,
                             config.provider_config['agent_token'],
                             config.provider_config.get('agent_tls_cert'),
                             config.provider_config.get('agent_tls_key')))
    os.chmod(os.path.join(cdir, 'node_start.sh'), 0o700)
    sbatch_path = os.path.join(cdir, 'job.sbatch')
    with open(sbatch_path, 'w', encoding='utf-8') as f:
        f.write(_sbatch_script(config, cdir))
    out = _run(['sbatch', '--parsable', sbatch_path])
    # --parsable: "<jobid>" or "<jobid>;<cluster>".
    return out.strip().split(';')[0]


def run_instances(config: ProvisionConfig) -> ClusterInfo:
    if config.num_slices > 1:
        raise exceptions.ProvisionError(
            'multislice (num_slices > 1) is supported on the gcp and '
            'local providers only', retryable=False)
    cdir = _cluster_dir(config.cluster_name)
    os.makedirs(cdir, exist_ok=True)
    job_id = _submit(config, cdir)
    _write_meta(cdir, {
        'cluster_name': config.cluster_name,
        'job_id': job_id,
        'num_hosts': config.num_hosts,
        'tpu_slice': config.tpu_slice,
        'instance_type': config.instance_type,
        'provider_config': {k: v for k, v in
                            config.provider_config.items()
                            if isinstance(v, (str, int, float, bool))},
        'created_at': time.time(),
    })
    info = get_cluster_info(config.cluster_name, config.provider_config)
    assert info is not None
    return info


def _job_status(job_id: str) -> tuple:
    """(state code, node hostnames) in ONE squeue round trip.

    A finished job ages out of squeue after MinJobAge; real squeue then
    prints 'Invalid job id' and exits NONZERO — that is the normal
    'GONE' case, not an error.
    """
    try:
        out = _run(['squeue', '-h', '-j', job_id, '-o', '%t %N'])
    except exceptions.ProvisionError as e:
        # Only the job-aged-out case is 'GONE'. A hung/timed-out
        # slurmctld is a transient control-plane outage — re-raise so
        # the caller retries instead of misreading it as a capacity
        # rejection and blocklisting the placement.
        if getattr(e, 'retryable', False):
            raise
        return 'GONE', []
    line = out.strip().splitlines()
    if not line:
        return 'GONE', []
    parts = line[0].split(None, 1)
    state = parts[0].strip()
    nodelist = parts[1].strip() if len(parts) > 1 else ''
    nodes: List[str] = []
    if state == 'R' and nodelist:
        nodes = _run(['scontrol', 'show', 'hostnames',
                      nodelist]).split()
    return state, nodes


def get_cluster_info(cluster_name: str,
                     provider_config: Dict[str, Any]
                     ) -> Optional[ClusterInfo]:
    cdir = _cluster_dir(cluster_name)
    meta = _meta(cdir)
    if meta is None:
        return None
    job_id = meta.get('job_id')
    state, nodes = _job_status(job_id) if job_id else ('GONE', [])
    host_state = {'R': 'RUNNING', 'PD': 'PROVISIONING',
                  'CG': 'STOPPED'}.get(state, 'STOPPED')
    if not nodes:
        # Not (or no longer) allocated: synthesize placeholders so the
        # host count survives for status displays.
        nodes = [f'<pending-{i}>' for i in range(meta['num_hosts'])]
    scheme = tls.scheme_for(
        meta.get('provider_config', {}).get('agent_tls_cert'))
    hosts = [HostInfo(
        host_id=f'{cluster_name}-node{i}',
        internal_ip=n,
        external_ip=n if not n.startswith('<') else None,
        state=host_state,
        agent_url=(f'{scheme}://{n}:{AGENT_PORT}'
                   if host_state == 'RUNNING' else None))
        for i, n in enumerate(nodes)]
    return ClusterInfo(
        cluster_name=cluster_name,
        cloud='slurm',
        region=meta.get('provider_config', {}).get('partition',
                                                   'default'),
        zone='slurm',
        hosts=hosts,
        tpu_slice=meta.get('tpu_slice'),
        instance_type=meta.get('instance_type'),
        cost_per_hour=0.0,     # on-prem allocation: sunk cost
        # cluster_dir routes the backend's file sync through the SHARED
        # FILESYSTEM (login node and compute nodes see the same
        # $SKY_TPU_HOME — the standard Slurm deployment): workdir sync
        # is a local copy into host<i>/workdir, exactly where each
        # node's agent runs jobs.
        provider_config={**meta.get('provider_config', {}),
                         'job_id': job_id, 'cluster_dir': cdir,
                         'agent_cert_fingerprint': tls.fingerprint_of_pem(
                             meta.get('provider_config', {})
                             .get('agent_tls_cert'))})


def wait_instances(cluster_name: str, provider_config: Dict[str, Any],
                   state: str = 'RUNNING') -> None:
    meta = _meta(_cluster_dir(cluster_name))
    if meta is None:
        raise exceptions.ClusterDoesNotExist(cluster_name)
    want = {'RUNNING': 'R'}.get(state, state)
    deadline = time.time() + float(
        provider_config.get('provision_timeout_s', 600))
    while time.time() < deadline:
        try:
            st, _ = _job_status(meta['job_id'])
        except exceptions.ProvisionError as e:
            # Transient slurmctld outage (squeue timeout): keep polling
            # until the deadline rather than aborting the attempt.
            if getattr(e, 'retryable', False):
                time.sleep(5)
                continue
            raise
        if st == want:
            return
        if st in ('F', 'CA', 'TO', 'NF', 'GONE'):
            raise exceptions.CapacityError(
                f'[slurm] job {meta["job_id"]} entered {st} '
                f'(queue rejected / failed)')
        if st in ('CD', 'BF', 'OOM', 'DL', 'PR'):
            # Allocated, ran, and already exited: the node payload
            # crashed (e.g. python missing on compute nodes) — fail fast
            # with the real cause, not a 10-minute "still queued?".
            raise exceptions.ProvisionError(
                f'[slurm] job {meta["job_id"]} exited immediately '
                f'({st}); check slurm.log in the cluster dir — the '
                f'agent payload likely failed on the compute nodes',
                retryable=False)
        time.sleep(5)
    raise exceptions.ProvisionTimeoutError(
        f'[slurm] job {meta["job_id"]} not {want} in time '
        f'(still queued? check the partition)')


def stop_instances(cluster_name: str,
                   provider_config: Dict[str, Any]) -> None:
    """Release the allocation; metadata survives for a later start."""
    meta = _meta(_cluster_dir(cluster_name))
    if meta and meta.get('job_id'):
        _run(['scancel', meta['job_id']])


def start_instances(cluster_name: str,
                    provider_config: Dict[str, Any]) -> ClusterInfo:
    cdir = _cluster_dir(cluster_name)
    meta = _meta(cdir)
    if meta is None:
        raise exceptions.ClusterDoesNotExist(cluster_name)
    cfg = ProvisionConfig(
        cluster_name=cluster_name, region='slurm', zone='slurm',
        instance_type=meta.get('instance_type') or 'slurm-node',
        num_hosts=meta['num_hosts'], tpu_slice=meta.get('tpu_slice'),
        provider_config={**meta.get('provider_config', {}),
                         **provider_config})
    meta['job_id'] = _submit(cfg, cdir)
    _write_meta(cdir, meta)
    info = get_cluster_info(cluster_name, provider_config)
    assert info is not None
    return info


def terminate_instances(cluster_name: str,
                        provider_config: Dict[str, Any]) -> None:
    cdir = _cluster_dir(cluster_name)
    meta = _meta(cdir)
    if meta and meta.get('job_id'):
        try:
            _run(['scancel', meta['job_id']])
        except exceptions.SkyTpuError:
            pass   # already gone
    shutil.rmtree(cdir, ignore_errors=True)


def open_ports(cluster_name: str, ports,
               provider_config: Dict[str, Any]) -> None:
    del cluster_name, ports, provider_config   # intra-cluster network


# Slurm compute nodes share the cluster network; no firewall layer to
# program. The capability honesty test accepts a no-op only with this
# marker.
open_ports.trivially_open = True
