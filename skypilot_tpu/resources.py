"""Hardware resource requests with TPU slices as first-class citizens.

Counterpart of the reference's ``sky/resources.py`` (Resources at :129,
AutostopConfig at :62, ``_set_accelerators`` at :861, ``less_demanding_than``
at :1814). The structural difference: ``accelerators='tpu-v5e-16'`` resolves
eagerly to a :class:`~skypilot_tpu.topology.TpuSlice`, so ``num_nodes`` for a
multi-host slice is *derived* (the slice's host count) rather than specified,
and no ``accelerator_args={'tpu_vm': True}`` escape hatch exists.
"""
from __future__ import annotations

import dataclasses
import re
from typing import Any, Dict, List, Optional, Tuple, Union

from skypilot_tpu import exceptions
from skypilot_tpu import topology

_ACC_RE = re.compile(r'^([A-Za-z0-9\-]+?)(?::(\d+))?$')

# Clouds known to the framework. 'local' is the in-process fake used by tests
# and the minimum-E2E path (reference analog: the mock_aws_backend fixture,
# reference tests/conftest.py:33).
KNOWN_CLOUDS = ('gcp', 'local', 'ssh', 'kubernetes', 'slurm')


@dataclasses.dataclass(frozen=True)
class AutostopConfig:
    """Autostop/autodown after idleness (reference sky/resources.py:62)."""
    enabled: bool = False
    idle_minutes: int = -1
    down: bool = False

    @classmethod
    def from_value(
        cls, value: Union[None, bool, int, Dict[str, Any]]
    ) -> Optional['AutostopConfig']:
        if value is None:
            return None
        if isinstance(value, bool):
            return cls(enabled=value, idle_minutes=5 if value else -1)
        if isinstance(value, int):
            return cls(enabled=True, idle_minutes=value)
        if isinstance(value, dict):
            return cls(enabled=True,
                       idle_minutes=int(value.get('idle_minutes', 5)),
                       down=bool(value.get('down', False)))
        raise exceptions.InvalidResourcesError(
            f'Invalid autostop value: {value!r}')

    def to_yaml_config(self) -> Union[bool, Dict[str, Any]]:
        if not self.enabled:
            return False
        return {'idle_minutes': self.idle_minutes, 'down': self.down}


def parse_accelerator(spec: Union[str, Dict[str, int], None]
                      ) -> Optional[Tuple[str, int]]:
    """Parse 'H100:8' / 'tpu-v5e-16' / {'A100': 4} → (name, count).

    For TPUs the count is implicit in the slice name; a ':N' suffix on a TPU
    name is rejected (the slice is the unit of allocation).
    """
    if spec is None:
        return None
    if isinstance(spec, dict):
        if len(spec) != 1:
            raise exceptions.InvalidResourcesError(
                f'accelerators dict must have exactly one entry: {spec!r}')
        name, count = next(iter(spec.items()))
        spec = f'{name}:{count}'
    m = _ACC_RE.match(str(spec).strip())
    if m is None:
        raise exceptions.InvalidResourcesError(
            f'Invalid accelerator spec: {spec!r}')
    name, count_s = m.group(1), m.group(2)
    if topology.is_tpu(name):
        if count_s is not None and int(count_s) != 1:
            raise exceptions.InvalidResourcesError(
                f'TPU slices are atomic; use the slice name alone '
                f'(got {spec!r}). e.g. accelerators: tpu-v5e-16')
        return (name, 1)
    return (name, int(count_s) if count_s else 1)


class Resources:
    """An immutable hardware request.

    Unset fields mean "let the optimizer choose" — mirroring the reference's
    Resources semantics where the optimizer fills in launchable candidates
    (reference sky/optimizer.py:1664 ``_fill_in_launchable_resources``).
    """

    def __init__(
        self,
        *,
        cloud: Optional[str] = None,
        region: Optional[str] = None,
        zone: Optional[str] = None,
        accelerators: Union[str, Dict[str, int], None] = None,
        cpus: Union[int, str, None] = None,
        memory: Union[int, str, None] = None,
        instance_type: Optional[str] = None,
        use_spot: bool = False,
        spot_recovery: Optional[str] = None,
        disk_size_gb: int = 256,
        image_id: Optional[str] = None,
        ports: Optional[List[int]] = None,
        autostop: Union[None, bool, int, Dict[str, Any]] = None,
        labels: Optional[Dict[str, str]] = None,
        runtime_version: Optional[str] = None,
        network_tier: Optional[str] = None,
        job_recovery: Optional[Union[str, Dict[str, Any]]] = None,
        any_of: Optional[List[Dict[str, Any]]] = None,
        num_slices: int = 1,
    ):
        if cloud is not None and cloud not in KNOWN_CLOUDS:
            raise exceptions.InvalidResourcesError(
                f'Unknown cloud {cloud!r}; known: {KNOWN_CLOUDS}')
        self._cloud = cloud
        self._region = region
        self._zone = zone
        acc = parse_accelerator(accelerators)
        self._accelerator_name: Optional[str] = acc[0] if acc else None
        self._accelerator_count: int = acc[1] if acc else 0
        self._tpu: Optional[topology.TpuSlice] = (
            topology.parse_tpu(self._accelerator_name)
            if self._accelerator_name else None)
        if self._tpu is not None:
            # Canonicalize spelling ('tpu-v5e-8'/'v5litepod-8' → 'v5e-8') so
            # __eq__/__hash__/round-trip treat identical slices identically.
            self._accelerator_name = self._tpu.name
        self._cpus = self._parse_scalar(cpus, 'cpus')
        self._memory = self._parse_scalar(memory, 'memory')
        self._instance_type = instance_type
        self._use_spot = bool(use_spot)
        self._spot_recovery = spot_recovery
        self._disk_size_gb = int(disk_size_gb)
        self._image_id = image_id
        self._ports = sorted(set(int(p) for p in ports)) if ports else []
        self._autostop = AutostopConfig.from_value(autostop)
        self._labels = dict(labels) if labels else {}
        # TPU software version (e.g. 'tpu-ubuntu2204-base', 'v2-alpha-tpuv5').
        self._runtime_version = runtime_version
        self._network_tier = network_tier
        self._job_recovery = job_recovery
        # `any_of`: list of alternative resource dicts (reference supports
        # this for multi-resource failover).
        self._any_of = [dict(a) for a in any_of] if any_of else None
        # Multislice: N identical TPU slices gang-allocated as ONE cluster,
        # connected over DCN (MEGASCALE_* wiring in runtime/distributed_env).
        self._num_slices = int(num_slices)
        if self._num_slices < 1:
            raise exceptions.InvalidResourcesError(
                f'num_slices must be >= 1, got {num_slices}')
        if self._num_slices > 1 and self._tpu is None:
            raise exceptions.InvalidResourcesError(
                'num_slices > 1 requires a TPU slice accelerator '
                '(multislice is DCN-connected TPU slices).')
        self._validate()

    # ---- parsing helpers -------------------------------------------------
    @staticmethod
    def _parse_scalar(value: Union[int, str, None],
                      what: str) -> Optional[Tuple[float, bool]]:
        """Returns (amount, is_minimum). '8+' → (8.0, True)."""
        if value is None:
            return None
        if isinstance(value, (int, float)):
            return (float(value), False)
        s = str(value).strip()
        plus = s.endswith('+')
        if plus:
            s = s[:-1]
        try:
            return (float(s), plus)
        except ValueError:
            raise exceptions.InvalidResourcesError(
                f'Invalid {what} spec: {value!r}') from None

    def _validate(self) -> None:
        if self._use_spot and self._autostop and self._autostop.enabled:
            # Allowed in the reference too; just a sanity check placeholder.
            pass

    # ---- accessors -------------------------------------------------------
    @property
    def cloud(self) -> Optional[str]:
        return self._cloud

    @property
    def region(self) -> Optional[str]:
        return self._region

    @property
    def zone(self) -> Optional[str]:
        return self._zone

    @property
    def accelerator_name(self) -> Optional[str]:
        return self._accelerator_name

    @property
    def accelerator_count(self) -> int:
        return self._accelerator_count

    @property
    def accelerators(self) -> Optional[Dict[str, int]]:
        if self._accelerator_name is None:
            return None
        return {self._accelerator_name: self._accelerator_count}

    @property
    def tpu(self) -> Optional[topology.TpuSlice]:
        return self._tpu

    @property
    def is_tpu(self) -> bool:
        return self._tpu is not None

    @property
    def num_hosts(self) -> int:
        """Host VMs implied by this request (1 for non-TPU), all slices."""
        per_slice = self._tpu.num_hosts if self._tpu else 1
        return per_slice * self._num_slices

    @property
    def num_slices(self) -> int:
        return self._num_slices

    @property
    def cpus(self) -> Optional[Tuple[float, bool]]:
        return self._cpus

    @property
    def memory(self) -> Optional[Tuple[float, bool]]:
        return self._memory

    @property
    def instance_type(self) -> Optional[str]:
        return self._instance_type

    @property
    def use_spot(self) -> bool:
        return self._use_spot

    @property
    def spot_recovery(self) -> Optional[str]:
        return self._spot_recovery

    @property
    def job_recovery(self):
        return self._job_recovery

    @property
    def disk_size_gb(self) -> int:
        return self._disk_size_gb

    @property
    def image_id(self) -> Optional[str]:
        return self._image_id

    @property
    def ports(self) -> List[int]:
        return list(self._ports)

    @property
    def autostop(self) -> Optional[AutostopConfig]:
        return self._autostop

    @property
    def labels(self) -> Dict[str, str]:
        return dict(self._labels)

    @property
    def runtime_version(self) -> Optional[str]:
        return self._runtime_version

    @property
    def network_tier(self) -> Optional[str]:
        return self._network_tier

    @property
    def any_of(self) -> Optional[List[Dict[str, Any]]]:
        return self._any_of

    # ---- transforms ------------------------------------------------------
    def copy(self, **override: Any) -> 'Resources':
        cfg = self.to_yaml_config()
        cfg.update(override)
        return Resources.from_yaml_config(cfg)

    def less_demanding_than(self, other: 'Resources') -> bool:
        """Can a cluster with `other` run a task asking `self`?

        Reference: sky/resources.py:1814. Used by `exec` to reuse clusters.
        """
        if self._cloud is not None and self._cloud != other._cloud:
            return False
        if self._region is not None and self._region != other._region:
            return False
        if self._zone is not None and self._zone != other._zone:
            return False
        if self._accelerator_name is not None:
            if self._tpu is not None:
                if other._tpu is None:
                    return False
                if (self._tpu.generation != other._tpu.generation or
                        self._tpu.num_chips > other._tpu.num_chips):
                    return False
            else:
                if (other._accelerator_name is None or
                        self._accelerator_name.lower() !=
                        other._accelerator_name.lower() or
                        self._accelerator_count > other._accelerator_count):
                    return False
        if self._use_spot and not other._use_spot:
            return False
        if self._cpus is not None and other._cpus is not None:
            if self._cpus[0] > other._cpus[0]:
                return False
        return True

    # ---- serialization ---------------------------------------------------
    @classmethod
    def from_yaml_config(cls, config: Optional[Dict[str, Any]]) -> 'Resources':
        config = dict(config or {})
        known = {
            'cloud', 'region', 'zone', 'accelerators', 'cpus', 'memory',
            'instance_type', 'use_spot', 'spot_recovery', 'disk_size_gb',
            'disk_size', 'image_id', 'ports', 'autostop', 'labels',
            'runtime_version', 'network_tier', 'job_recovery', 'any_of',
            'num_slices',
        }
        unknown = set(config) - known
        if unknown:
            raise exceptions.InvalidResourcesError(
                f'Unknown resources fields: {sorted(unknown)}')
        if 'disk_size' in config:
            config['disk_size_gb'] = config.pop('disk_size')
        return cls(**config)

    def to_yaml_config(self) -> Dict[str, Any]:
        cfg: Dict[str, Any] = {}
        if self._cloud:
            cfg['cloud'] = self._cloud
        if self._region:
            cfg['region'] = self._region
        if self._zone:
            cfg['zone'] = self._zone
        if self._accelerator_name:
            if self._tpu is not None or self._accelerator_count == 1:
                cfg['accelerators'] = self._accelerator_name
            else:
                cfg['accelerators'] = (
                    f'{self._accelerator_name}:{self._accelerator_count}')
        if self._cpus is not None:
            cfg['cpus'] = (f'{self._cpus[0]:g}+'
                           if self._cpus[1] else self._cpus[0])
        if self._memory is not None:
            cfg['memory'] = (f'{self._memory[0]:g}+'
                             if self._memory[1] else self._memory[0])
        if self._instance_type:
            cfg['instance_type'] = self._instance_type
        if self._use_spot:
            cfg['use_spot'] = True
        if self._spot_recovery:
            cfg['spot_recovery'] = self._spot_recovery
        if self._disk_size_gb != 256:
            cfg['disk_size_gb'] = self._disk_size_gb
        if self._image_id:
            cfg['image_id'] = self._image_id
        if self._ports:
            cfg['ports'] = list(self._ports)
        if self._autostop is not None:
            cfg['autostop'] = self._autostop.to_yaml_config()
        if self._labels:
            cfg['labels'] = dict(self._labels)
        if self._runtime_version:
            cfg['runtime_version'] = self._runtime_version
        if self._network_tier:
            cfg['network_tier'] = self._network_tier
        if self._job_recovery:
            cfg['job_recovery'] = self._job_recovery
        if self._any_of:
            cfg['any_of'] = [dict(a) for a in self._any_of]
        if self._num_slices != 1:
            cfg['num_slices'] = self._num_slices
        return cfg

    def __eq__(self, other: object) -> bool:
        if not isinstance(other, Resources):
            return NotImplemented
        return self.to_yaml_config() == other.to_yaml_config()

    def __hash__(self) -> int:
        import json
        return hash(json.dumps(self.to_yaml_config(), sort_keys=True))

    def __repr__(self) -> str:
        parts = []
        if self._cloud:
            parts.append(self._cloud)
        if self._instance_type:
            parts.append(self._instance_type)
        if self._accelerator_name:
            if self._tpu:
                parts.append(str(self._tpu))
            else:
                parts.append(
                    f'{self._accelerator_name}:{self._accelerator_count}')
        if self._use_spot:
            parts.append('[spot]')
        if self._region:
            parts.append(f'region={self._region}')
        return f'Resources({", ".join(parts) or "default"})'
