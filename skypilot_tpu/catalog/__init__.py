"""Accelerator/instance catalog with TPU slices priced parametrically.

Counterpart of the reference's ``sky/catalog/`` (12,146 LoC of per-cloud CSV
loaders; TPU grouping/pricing in gcp_catalog.py:486-566). Two structural
changes for the TPU-first design:

1. TPU entries are stored **per chip-hour per generation+region**, and slice
   prices are computed from :class:`~skypilot_tpu.topology.TpuSlice` chip
   counts — every valid slice size is automatically priced, instead of the
   reference's approach of materializing one CSV row per slice size.
2. The catalog is bundled (no hosted-catalog fetch, reference
   sky/skylet/constants.py:614) — prices are a static snapshot; a
   ``refresh()`` hook exists for wiring a fetcher later.

The `local` cloud is always feasible and free: it provisions fake slices of
local processes (the test/E2E backend, reference analog mock_aws_backend).
"""
from __future__ import annotations

import csv
import dataclasses
import functools
import os
from typing import Dict, List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu import topology

_DATA_DIR = os.path.join(os.path.dirname(__file__), 'data')


@functools.lru_cache(maxsize=None)
def _az_mappings(cloud: str) -> Dict[tuple, List[str]]:
    """(region, generation) → zones with that TPU generation, from the
    bundled <cloud>_az_mappings.csv (reference ships az-mapping CSVs per
    cloud; the failover loop walks them zone by zone)."""
    path = os.path.join(_DATA_DIR, f'{cloud}_az_mappings.csv')
    out: Dict[tuple, List[str]] = {}
    if not os.path.exists(path):
        return out
    with open(path, newline='', encoding='utf-8') as f:
        for row in csv.DictReader(f):
            for gen in (row.get('tpu_generations') or '').split(';'):
                gen = gen.strip()
                if gen:
                    out.setdefault((row['region'], gen),
                                   []).append(row['zone'])
    return out


def zones_for(cloud: str, region: str, generation: str,
              default_zone: str) -> List[str]:
    """All zones of `region` offering `generation`.

    The az-mapping is authoritative when it has an entry — the catalog
    row's representative zone may not actually carry this generation
    (e.g. v6e sits in us-east5-b while the price row's zone is -a), and
    a candidate in a zone without the TPU guarantees a provision
    failure. The row's zone is only the fallback for unmapped regions.
    """
    zones = _az_mappings(cloud).get((region, generation))
    return list(zones) if zones else [default_zone]

# Egress $/GiB (reference models this in sky/optimizer.py's egress cost).
SAME_REGION_EGRESS = 0.0
CROSS_REGION_EGRESS = 0.01
CROSS_CLOUD_EGRESS = 0.09


@dataclasses.dataclass(frozen=True)
class CatalogEntry:
    """One raw catalog row."""
    cloud: str
    kind: str                 # 'tpu' | 'gpu' | 'cpu'
    name: str                 # tpu generation / gpu name / instance type
    region: str
    zone: str
    price: float              # per chip-hour (tpu), per gpu-hour (gpu),
                              # per instance-hour (cpu)
    spot_price: float
    vcpus: Optional[float]
    memory_gb: Optional[float]


@dataclasses.dataclass(frozen=True)
class Candidate:
    """A launchable placement candidate produced for the optimizer."""
    cloud: str
    region: str
    zone: str
    instance_type: str        # e.g. 'tpu-v5e-16', 'a3-highgpu-8g-ish', cpu type
    accelerator_name: Optional[str]
    accelerator_count: int
    use_spot: bool
    cost_per_hour: float      # whole-allocation (all hosts of a slice)
    num_hosts: int
    tpu: Optional[topology.TpuSlice] = None

    def __str__(self) -> str:
        spot = '[spot]' if self.use_spot else ''
        acc = (f', {self.accelerator_name}:{self.accelerator_count}'
               if self.accelerator_name else '')
        return (f'{self.cloud}({self.region}/{self.zone}, '
                f'{self.instance_type}{acc}){spot} '
                f'${self.cost_per_hour:.2f}/hr')


@functools.lru_cache(maxsize=None)
def _load(cloud: str) -> List[CatalogEntry]:
    path = os.path.join(_DATA_DIR, f'{cloud}.csv')
    if not os.path.exists(path):
        return []
    out: List[CatalogEntry] = []
    with open(path, newline='', encoding='utf-8') as f:
        for row in csv.DictReader(f):
            out.append(CatalogEntry(
                cloud=cloud,
                kind=row['kind'].strip(),
                name=row['name'].strip(),
                region=row['region'].strip(),
                zone=row['zone'].strip(),
                price=float(row['price']),
                spot_price=float(row['spot_price'] or row['price']),
                vcpus=float(row['vcpus']) if row.get('vcpus') else None,
                memory_gb=(float(row['memory_gb'])
                           if row.get('memory_gb') else None),
            ))
    return out


@functools.lru_cache(maxsize=None)
def preemption_rates(cloud: str) -> Dict[tuple, float]:
    """(generation, region, zone) → observed spot preemptions per
    slice-hour, from the bundled <cloud>_preemption.csv. A static
    seed snapshot, like the price catalog: the serve tier's
    FleetCatalog (serve/costplane/) layers a pluggable fetcher and
    staleness handling on top of it. Missing file → empty (every rate
    reads as the conservative default the caller picks)."""
    path = os.path.join(_DATA_DIR, f'{cloud}_preemption.csv')
    out: Dict[tuple, float] = {}
    if not os.path.exists(path):
        return out
    with open(path, newline='', encoding='utf-8') as f:
        for row in csv.DictReader(f):
            out[(row['name'].strip(), row['region'].strip(),
                 row['zone'].strip())] = float(
                     row['preemption_rate_per_hour'])
    return out


def refresh() -> None:
    """Drop cached catalog data (hook for a future hosted-catalog fetcher)."""
    _load.cache_clear()
    _az_mappings.cache_clear()
    preemption_rates.cache_clear()


def list_accelerators(name_filter: Optional[str] = None,
                      clouds: Optional[List[str]] = None
                      ) -> Dict[str, List[Dict]]:
    """`sky-tpu show-accelerators` backing data.

    For TPUs, expands each generation into its standard slice sizes with
    whole-slice pricing.
    """
    result: Dict[str, List[Dict]] = {}
    for cloud in clouds or ['gcp']:
        for e in _load(cloud):
            if e.kind == 'cpu':
                continue
            if e.kind == 'tpu':
                gen = topology.TPU_GENERATIONS[e.name]
                sizes = [1, 2, 4, 8, 16, 32, 64, 128, 256]
                for chips in sizes:
                    suffix = (chips * gen.cores_per_chip
                              if gen.suffix_counts_cores else chips)
                    try:
                        s = topology.parse_tpu(f'{e.name}-{suffix}')
                    except exceptions.InvalidResourcesError:
                        continue
                    if name_filter and name_filter.lower() not in s.name:
                        continue
                    result.setdefault(s.name, []).append({
                        'cloud': cloud, 'region': e.region,
                        'price': e.price * s.num_chips,
                        'spot_price': e.spot_price * s.num_chips,
                        'num_hosts': s.num_hosts,
                        'chips': s.num_chips,
                        'topology': s.topology_str,
                    })
            else:
                if name_filter and name_filter.lower() not in e.name.lower():
                    continue
                result.setdefault(e.name, []).append({
                    'cloud': cloud, 'region': e.region, 'price': e.price,
                    'spot_price': e.spot_price, 'num_hosts': 1,
                })
    return result


def get_candidates(resources: 'Resources',  # noqa: F821
                   required=None) -> List[Candidate]:
    """All feasible (cloud, region, zone, instance) placements for a request.

    The optimizer's feasibility+pricing source (reference
    sky/optimizer.py:1664 ``_fill_in_launchable_resources``).

    `required` (a frozenset of cloud_capabilities.Feature) filters clouds
    declaratively: a pinned cloud missing a feature raises with the
    feature names; unpinned requests silently skip infeasible clouds
    (reference CloudImplementationFeatures gating).
    """
    from skypilot_tpu import resources as resources_lib
    assert isinstance(resources, resources_lib.Resources)
    out: List[Candidate] = []
    if resources.cloud:
        if required:
            from skypilot_tpu import cloud_capabilities as caps
            caps.check_features(resources.cloud, required)
        clouds = [resources.cloud]
    else:
        # Unpinned requests consider enabled *priced* clouds only. The
        # $0.00/hr clouds (local fake, sunk-cost ssh pools, in-cluster
        # kubernetes) would win every cost ranking — they must be pinned
        # explicitly with `cloud: ...`.
        from skypilot_tpu import state
        enabled = [c for c in state.get_enabled_clouds()
                   if c not in ('local', 'ssh', 'kubernetes', 'slurm')]
        clouds = enabled or ['gcp']
        if required:
            from skypilot_tpu import cloud_capabilities as caps
            clouds = [c for c in clouds
                      if not caps.unsupported(c, required)]

    for cloud in clouds:
        if cloud == 'local':
            out.append(_local_candidate(resources))
            continue
        if cloud == 'ssh':
            out.extend(_ssh_pool_candidates(resources))
            continue
        if cloud == 'kubernetes':
            cand = _k8s_candidate(resources)
            if cand is not None:
                out.append(cand)
            continue
        if cloud == 'slurm':
            out.append(_slurm_candidate(resources))
            continue
        for e in _load(cloud):
            if resources.region and e.region != resources.region:
                continue
            price = e.spot_price if resources.use_spot else e.price
            if resources.is_tpu:
                s = resources.tpu
                if e.kind != 'tpu' or e.name != s.generation:
                    continue
                # az-mappings widen the failover surface: the catalog
                # prices per region with one representative zone, but a
                # region usually has several zones with that generation
                # (reference az-mapping CSVs, gcp_catalog.py:486-566).
                for zone in zones_for(cloud, e.region, e.name, e.zone):
                    if resources.zone and zone != resources.zone:
                        continue
                    out.append(Candidate(
                        cloud=cloud, region=e.region, zone=zone,
                        instance_type=f'tpu-{s.name}',
                        accelerator_name=s.name, accelerator_count=1,
                        use_spot=resources.use_spot,
                        cost_per_hour=price * s.num_chips,
                        num_hosts=s.num_hosts, tpu=s))
                continue
            if resources.zone and e.zone != resources.zone:
                continue
            if resources.accelerator_name is not None:
                if (e.kind != 'gpu' or
                        e.name.lower() !=
                        resources.accelerator_name.lower()):
                    continue
                n = resources.accelerator_count
                if resources.cpus and (e.vcpus or 0) * n < resources.cpus[0]:
                    continue
                if (resources.memory and
                        (e.memory_gb or 0) * n < resources.memory[0]):
                    continue
                out.append(Candidate(
                    cloud=cloud, region=e.region, zone=e.zone,
                    instance_type=f'{e.name.lower()}x{n}',
                    accelerator_name=e.name, accelerator_count=n,
                    use_spot=resources.use_spot,
                    cost_per_hour=price * n, num_hosts=1))
            else:
                if e.kind != 'cpu':
                    continue
                if resources.instance_type and e.name != \
                        resources.instance_type:
                    continue
                # '8+' is a minimum; bare '8' means exactly 8 (the
                # reference's cpus/memory semantics).
                if resources.cpus:
                    amount, is_min = resources.cpus
                    have = e.vcpus or 0
                    if have < amount or (not is_min and have != amount):
                        continue
                if resources.memory:
                    amount, is_min = resources.memory
                    have = e.memory_gb or 0
                    if have < amount or (not is_min and have != amount):
                        continue
                out.append(Candidate(
                    cloud=cloud, region=e.region, zone=e.zone,
                    instance_type=e.name, accelerator_name=None,
                    accelerator_count=0, use_spot=resources.use_spot,
                    cost_per_hour=price, num_hosts=1))
    return out


def _local_candidate(resources: 'Resources') -> Candidate:  # noqa: F821
    """The local fake cloud: free, any shape, N-host slices become N local
    processes."""
    tpu = resources.tpu
    return Candidate(
        cloud='local', region='local', zone='local',
        instance_type=(f'tpu-{tpu.name}' if tpu else
                       resources.instance_type or 'local-vm'),
        accelerator_name=resources.accelerator_name,
        accelerator_count=resources.accelerator_count,
        use_spot=resources.use_spot,
        cost_per_hour=0.0,
        num_hosts=tpu.num_hosts if tpu else 1,
        tpu=tpu)


def _k8s_candidate(resources: 'Resources') -> Optional[Candidate]:  # noqa: F821,E501
    """In-cluster placement: the GKE cluster is sunk cost ($0/hr); slice
    shape still gangs via the TPU topology (provision/k8s renders the
    StatefulSet from it)."""
    from skypilot_tpu import config as config_lib
    tpu = resources.tpu
    # region pins the kubeconfig context, zone the namespace (the k8s
    # analog of placement); config supplies defaults.
    ctx = resources.region or config_lib.get_nested(
        ('kubernetes', 'context'), 'in-cluster')
    ns = resources.zone or config_lib.get_nested(
        ('kubernetes', 'namespace'), 'default')
    return Candidate(
        cloud='kubernetes', region=ctx, zone=ns,
        instance_type=(f'tpu-{tpu.name}' if tpu else
                       resources.instance_type or 'pod'),
        accelerator_name=resources.accelerator_name,
        accelerator_count=resources.accelerator_count,
        use_spot=resources.use_spot,
        cost_per_hour=0.0,
        num_hosts=tpu.num_hosts if tpu else 1,
        tpu=tpu)


def _slurm_candidate(resources: 'Resources') -> Candidate:  # noqa: F821
    """Slurm allocation as a placement: on-prem sunk cost ($0/hr), gang
    size from the TPU slice (or num_nodes); region carries the
    partition (config default otherwise)."""
    from skypilot_tpu import config as config_lib
    tpu = resources.tpu
    partition = resources.region or config_lib.get_nested(
        ('slurm', 'partition'), 'default')
    return Candidate(
        cloud='slurm', region=partition, zone='slurm',
        instance_type=(f'tpu-{tpu.name}' if tpu else
                       resources.instance_type or 'slurm-node'),
        accelerator_name=resources.accelerator_name,
        accelerator_count=resources.accelerator_count,
        use_spot=resources.use_spot,
        cost_per_hour=0.0,
        num_hosts=tpu.num_hosts if tpu else 1,
        tpu=tpu)


def _ssh_pool_candidates(resources: 'Resources') -> List[Candidate]:  # noqa: F821,E501
    """Bare-metal pools as placements: `cloud: ssh` with instance_type
    naming the pool (all pools when unpinned). Pools are sunk cost —
    $0/hr — and gang-shaped by their host list; a pool declaring
    ``accelerator: v4-16`` carries TPU topology."""
    from skypilot_tpu import topology as topology_lib
    from skypilot_tpu.ssh_node_pools import SSHNodePoolManager
    pools = SSHNodePoolManager().get_all_pools()
    if resources.instance_type:
        pools = {k: v for k, v in pools.items()
                 if k == resources.instance_type}
    out: List[Candidate] = []
    for name, cfg in pools.items():
        tpu = None
        acc = cfg.get('accelerator')
        if acc:
            try:
                tpu = topology_lib.parse_tpu(acc)
            except Exception:  # noqa: BLE001 — non-TPU accelerator pools
                tpu = None
        if resources.tpu is not None and (
                tpu is None or tpu.name != resources.tpu.name):
            continue
        out.append(Candidate(
            cloud='ssh', region=cfg.get('region', 'pool'),
            zone=name, instance_type=name,
            accelerator_name=(resources.accelerator_name
                              if tpu is None else tpu.name),
            accelerator_count=resources.accelerator_count,
            use_spot=False, cost_per_hour=0.0,
            num_hosts=len(cfg['hosts']), tpu=tpu))
    return out


def egress_cost_per_gib(src: Candidate, dst: Candidate) -> float:
    if src.cloud != dst.cloud:
        return CROSS_CLOUD_EGRESS
    if src.region != dst.region:
        return CROSS_REGION_EGRESS
    return SAME_REGION_EGRESS
