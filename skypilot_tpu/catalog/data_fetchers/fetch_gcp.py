"""Regenerate the bundled GCP catalog CSV (reference
``sky/catalog/data_fetchers/fetch_gcp.py``: queries the Cloud Billing
Catalog API and writes the hosted CSVs this framework bundles instead).

Online mode walks the Cloud Billing Catalog API
(``cloudbilling.googleapis.com/v1/services/<compute-service>/skus``)
for TPU/GPU SKUs and converts nanos -> $/chip-hour rows. ``--offline``
(the default in air-gapped environments) re-emits the audited built-in
snapshot so the pipeline stays runnable end-to-end without credentials.

Usage:
    python -m skypilot_tpu.catalog.data_fetchers.fetch_gcp \
        [--offline] [--output <path>]
"""
from __future__ import annotations

import argparse
import csv
import os
from typing import Dict, Iterator, List, Optional

# Compute Engine's service id in the billing catalog (stable, public).
_COMPUTE_SERVICE = 'services/6F81-5844-456A'
_BILLING_API = 'https://cloudbilling.googleapis.com/v1'

_HEADER = ['kind', 'name', 'region', 'zone', 'price', 'spot_price',
           'vcpus', 'memory_gb', 'notes']

# TPU SKU descriptions encode generation; map onto catalog names.
_TPU_DESC_TO_GEN = {
    'tpu v2': 'v2',
    'tpu v3': 'v3',
    'tpu v4': 'v4',
    'tpu v5 lite': 'v5e',
    'tpu v5e': 'v5e',
    'tpu v5p': 'v5p',
    'tpu v6e': 'v6e',
    'trillium': 'v6e',
}

# Region -> a representative zone with TPU capacity (the API prices per
# region; the provisioner needs a concrete zone — the az-mappings CSV
# then widens each row to every zone carrying that generation).
_DEFAULT_ZONE = {
    'us-central1': 'us-central1-a',
    'us-central2': 'us-central2-b',
    'us-east1': 'us-east1-c',
    'us-east5': 'us-east5-a',
    'us-west1': 'us-west1-c',
    'us-west4': 'us-west4-a',
    'us-south1': 'us-south1-a',
    'europe-west1': 'europe-west1-c',
    'europe-west4': 'europe-west4-a',
    'asia-east1': 'asia-east1-c',
    'asia-southeast1': 'asia-southeast1-b',
    'asia-northeast1': 'asia-northeast1-b',
    'asia-south1': 'asia-south1-a',
    'southamerica-west1': 'southamerica-west1-a',
}


def _iter_skus(token: Optional[str] = None) -> Iterator[Dict]:
    """Pages through the billing catalog (online mode)."""
    import requests
    page: Optional[str] = None
    while True:
        params = {'pageSize': 500}
        if page:
            params['pageToken'] = page
        headers = {}
        if token:
            headers['Authorization'] = f'Bearer {token}'
        else:
            key = os.environ.get('GCP_API_KEY')
            if key:
                params['key'] = key
        r = requests.get(f'{_BILLING_API}/{_COMPUTE_SERVICE}/skus',
                         params=params, headers=headers, timeout=60)
        r.raise_for_status()
        body = r.json()
        yield from body.get('skus', [])
        page = body.get('nextPageToken')
        if not page:
            return


def _sku_unit_price(sku: Dict) -> Optional[float]:
    infos = sku.get('pricingInfo') or []
    if not infos:
        return None
    tiers = (infos[0].get('pricingExpression') or {}).get(
        'tieredRates') or []
    if not tiers:
        return None
    money = tiers[-1].get('unitPrice') or {}
    return (float(money.get('units') or 0) +
            float(money.get('nanos') or 0) / 1e9)


def _convert_skus(skus) -> List[List]:
    """Billing-catalog SKU objects → catalog TPU rows (shared by the
    online API walk and the canned-fixture path, so the fixture test
    exercises the REAL conversion)."""
    rows: List[List] = []
    for sku in skus:
        desc = (sku.get('description') or '').lower()
        gen = next((g for d, g in _TPU_DESC_TO_GEN.items() if d in desc),
                   None)
        if gen is None or 'pod' in desc and 'slice' not in desc:
            continue
        spot = ('preemptible' in desc or 'spot' in desc)
        price = _sku_unit_price(sku)
        if price is None or price <= 0:
            continue
        for region in (sku.get('serviceRegions') or []):
            zone = _DEFAULT_ZONE.get(region)
            if zone is None:
                continue
            rows.append(['tpu', gen, region, zone,
                         '' if spot else f'{price:.4f}',
                         f'{price:.4f}' if spot else '',
                         '', '', 'per-chip-hour'])
    return _merge_spot(rows)


def fetch_online(token: Optional[str] = None) -> List[List]:
    """TPU rows from the live billing catalog + maintained comparators."""
    return _convert_skus(_iter_skus(token)) + comparator_rows()


def fetch_from_fixture(path: Optional[str] = None) -> List[List]:
    """TPU rows from a canned billing-API response (offline CI), through
    the same conversion as the live walk, + maintained comparators."""
    import json
    path = path or os.path.join(os.path.dirname(os.path.abspath(
        __file__)), 'fixtures', 'gcp_billing_skus.json')
    with open(path, encoding='utf-8') as f:
        return _convert_skus(json.load(f)['skus']) + comparator_rows()


def comparator_rows() -> List[List]:
    """GPU/CPU comparator rows (maintained here, not fetched: the GPU
    market prices move slowly and the optimizer only needs them for
    TPU-vs-GPU cost ranking)."""
    bundled = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           'fixtures', 'gcp_comparators.csv')
    with open(bundled, newline='', encoding='utf-8') as f:
        reader = csv.reader(f)
        next(reader)   # header
        return [row for row in reader if row]


def _merge_spot(rows: List[List]) -> List[List]:
    """Collapse separate on-demand/spot SKU rows into one CSV row."""
    merged: Dict[tuple, List] = {}
    for r in rows:
        key = (r[0], r[1], r[2], r[3])
        cur = merged.setdefault(
            key, [r[0], r[1], r[2], r[3], '', '', '', '', r[8]])
        if r[4]:
            cur[4] = r[4]
        if r[5]:
            cur[5] = r[5]
    out = []
    for cur in merged.values():
        if not cur[4]:
            continue   # spot-only rows are unusable without on-demand
        if not cur[5]:
            cur[5] = f'{float(cur[4]) * 0.3:.4f}'   # GCP spot ~70% off
        out.append(cur)
    return out


def fetch_offline() -> List[List]:
    """Re-emit the audited bundled snapshot (air-gapped mode)."""
    bundled = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), 'data', 'gcp.csv')
    with open(bundled, newline='', encoding='utf-8') as f:
        reader = csv.reader(f)
        next(reader)   # header
        return [row for row in reader if row]


def write_csv(rows: List[List], output: str) -> None:
    tmp = f'{output}.{os.getpid()}.tmp'
    with open(tmp, 'w', newline='', encoding='utf-8') as f:
        w = csv.writer(f)
        w.writerow(_HEADER)
        w.writerows(rows)
    os.replace(tmp, output)


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument('--offline', action='store_true',
                        help='re-emit the bundled snapshot (no network)')
    parser.add_argument('--fixture', action='store_true',
                        help='generate from the canned billing-API '
                             'fixture (what the shipped CSV is built '
                             'from; no network)')
    parser.add_argument('--output', default=None,
                        help='output CSV (default: the bundled gcp.csv)')
    args = parser.parse_args()
    output = args.output or os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        'data', 'gcp.csv')
    rows = (fetch_from_fixture() if args.fixture else
            fetch_offline() if args.offline else fetch_online())
    if not rows:
        raise SystemExit('fetched 0 rows; refusing to write an empty '
                         'catalog')
    write_csv(rows, output)
    print(f'wrote {len(rows)} rows to {output}')


if __name__ == '__main__':
    main()
