"""Volume server ops (reference ``sky/volumes/server/core.py``:
volume_apply :303, volume_list :169, volume_delete :247,
volume_refresh :28, per-volume lock :428)."""
from __future__ import annotations

import logging
from typing import Any, Dict, List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu import state
from skypilot_tpu.utils import locks
from skypilot_tpu.volumes.volume import Volume, VolumeType

logger = logging.getLogger(__name__)


def _create_backend_resource(vol: Volume) -> None:
    """Create the backing resource for non-existing volumes."""
    if vol.type == VolumeType.GCP_PD and not vol.use_existing:
        from skypilot_tpu.provision.gcp import tpu_api
        client = tpu_api.GceDiskClient(
            vol.config.get('project') or tpu_api.default_project())
        client.create_disk(vol.zone, vol.name, vol.size_gb,
                           disk_type=vol.config.get('disk_type',
                                                    'pd-balanced'))
    elif vol.type == VolumeType.K8S_PVC and not vol.use_existing:
        from skypilot_tpu.provision.k8s import instance as k8s_instance
        k8s_instance.create_pvc(vol.name, vol.size_gb, vol.config)
    # gcsfuse/hostpath: backing store is created lazily at mount time
    # (bucket must already exist or be creatable by the storage layer).


def volume_apply(cfg: Dict[str, Any]) -> Dict[str, Any]:
    """Create/register a volume (idempotent). Reference :303."""
    vol = Volume.from_yaml_config(cfg)
    with locks.named_lock(f'volume_{vol.name}'):
        existing = state.get_volume(vol.name)
        if existing is not None:
            if existing['type'] != vol.type.value:
                raise exceptions.InvalidTaskError(
                    f'Volume {vol.name!r} already exists with type '
                    f'{existing["type"]} != {vol.type.value}.')
            return existing
        _create_backend_resource(vol)
        state.add_or_update_volume(
            vol.name, vol_type=vol.type.value, cloud=vol.cloud,
            region=vol.region, zone=vol.zone, size_gb=vol.size_gb,
            # use_existing must survive into the record: delete consults
            # it to decide whether the backing resource is OURS to
            # destroy (deleting a user-owned PVC/PD would eat data).
            config={**vol.config, 'use_existing': vol.use_existing},
            status='READY')
    return state.get_volume(vol.name)


def volume_list() -> List[Dict[str, Any]]:
    return state.get_volumes()


def volume_delete(names: List[str]) -> None:
    """Reference :247 — refuses while a cluster uses the volume."""
    for name in names:
        with locks.named_lock(f'volume_{name}'):
            rec = state.get_volume(name)
            if rec is None:
                raise exceptions.VolumeNotFoundError(
                    f'No such volume: {name}')
            if rec['status'] == 'IN_USE':
                raise exceptions.VolumeError(
                    f'Volume {name!r} is attached to '
                    f'{rec["attached_to"]!r}; detach (down the cluster) '
                    f'first.')
            if (rec['type'] == VolumeType.GCP_PD.value and
                    not rec['config'].get('use_existing')):
                from skypilot_tpu.provision.gcp import tpu_api
                client = tpu_api.GceDiskClient(
                    rec['config'].get('project') or
                    tpu_api.default_project())
                client.delete_disk(rec['zone'], name)
            elif (rec['type'] == VolumeType.K8S_PVC.value and
                    not rec['config'].get('use_existing')):
                from skypilot_tpu.provision.k8s import (
                    instance as k8s_instance)
                k8s_instance.delete_pvc(name, rec['config'])
            state.remove_volume(name)


def volume_refresh() -> None:
    """Reconcile IN_USE volumes whose cluster is gone (reference :28)."""
    for rec in state.get_volumes():
        if rec['status'] != 'IN_USE':
            continue
        cluster = rec.get('attached_to')
        if cluster and state.get_cluster(cluster) is None:
            logger.info('volume %s: cluster %s gone; marking READY',
                        rec['name'], cluster)
            state.set_volume_status(rec['name'], 'READY')


def attach(name: str, cluster_name: str) -> Dict[str, Any]:
    """Mark attached + return the record (used by the backend at mount
    time)."""
    with locks.named_lock(f'volume_{name}'):
        rec = state.get_volume(name)
        if rec is None:
            raise exceptions.VolumeNotFoundError(f'No such volume: {name}')
        if rec['status'] == 'IN_USE' and rec['attached_to'] != cluster_name:
            raise exceptions.VolumeError(
                f'Volume {name!r} is already attached to '
                f'{rec["attached_to"]!r}.')
        state.set_volume_status(name, 'IN_USE', attached_to=cluster_name)
        return state.get_volume(name)


def detach_all(cluster_name: str) -> None:
    """Release every volume held by `cluster_name` (teardown path)."""
    for rec in state.get_volumes():
        if rec.get('attached_to') == cluster_name:
            state.set_volume_status(rec['name'], 'READY')


def to_volume(rec: Dict[str, Any]) -> Volume:
    return Volume(name=rec['name'], type=VolumeType(rec['type']),
                  cloud=rec['cloud'], region=rec['region'],
                  zone=rec['zone'], size_gb=rec['size_gb'],
                  use_existing=True, config=rec['config'])
