"""Volume model (reference ``sky/volumes/volume.py``: ``Volume`` :25 with
``PVCVolume``/``HostPathVolume`` subclasses and a from_yaml_config
factory).

TPU-native volume types replace the reference's k8s-PVC focus:

- ``gcp-pd``: a GCE persistent disk in the slice's zone, attached to TPU
  VM hosts as a data disk (the TPU API's dataDisks field).
- ``gcsfuse``: a GCS bucket mounted via gcsfuse — the idiomatic TPU
  checkpoint/dataset volume; "size" is advisory (buckets are unbounded).
- ``hostpath``: a host directory bind (single-host dev and the local
  fake slice).
"""
from __future__ import annotations

import dataclasses
import enum
import re
from typing import Any, Dict, Optional

from skypilot_tpu import exceptions


class VolumeType(str, enum.Enum):
    GCP_PD = 'gcp-pd'
    GCSFUSE = 'gcsfuse'
    HOSTPATH = 'hostpath'
    K8S_PVC = 'k8s-pvc'


_SIZE_RE = re.compile(r'^(\d+)\s*(Gi|G|Ti|T)?$', re.IGNORECASE)


def parse_size_gb(size: Optional[str]) -> Optional[int]:
    """'100Gi' / '100' -> 100; '1Ti' -> 1024."""
    if size is None:
        return None
    m = _SIZE_RE.match(str(size).strip())
    if not m:
        raise exceptions.InvalidTaskError(
            f'Invalid volume size {size!r} (expected e.g. "100Gi").')
    n = int(m.group(1))
    unit = (m.group(2) or 'G').lower()
    return n * 1024 if unit.startswith('t') else n


@dataclasses.dataclass
class Volume:
    """A named persistent volume (reference volume.py:25)."""
    name: str
    type: VolumeType
    cloud: str = 'gcp'
    region: Optional[str] = None
    zone: Optional[str] = None
    size_gb: Optional[int] = None
    use_existing: bool = False
    config: Dict[str, Any] = dataclasses.field(default_factory=dict)

    def __post_init__(self) -> None:
        if not self.name:
            raise exceptions.InvalidTaskError('Volume needs a name.')
        if self.type == VolumeType.GCP_PD and not self.use_existing:
            if self.size_gb is None:
                raise exceptions.InvalidTaskError(
                    f'gcp-pd volume {self.name!r} needs a size.')
            if self.zone is None:
                raise exceptions.InvalidTaskError(
                    f'gcp-pd volume {self.name!r} needs a zone '
                    f'(PDs are zonal; must match the TPU slice zone).')
        if self.type == VolumeType.GCSFUSE and not self.config.get(
                'bucket'):
            # Default bucket name: the volume name.
            self.config['bucket'] = self.name
        if self.type == VolumeType.HOSTPATH and not self.config.get(
                'path'):
            raise exceptions.InvalidTaskError(
                f'hostpath volume {self.name!r} needs config.path.')
        if self.type == VolumeType.K8S_PVC:
            self.cloud = 'kubernetes'
            if self.size_gb is None and not self.use_existing:
                raise exceptions.InvalidTaskError(
                    f'k8s-pvc volume {self.name!r} needs a size.')

    @classmethod
    def from_yaml_config(cls, cfg: Dict[str, Any]) -> 'Volume':
        try:
            vt = VolumeType(cfg.get('type'))
        except ValueError:
            raise exceptions.InvalidTaskError(
                f'Invalid volume type {cfg.get("type")!r}; supported: '
                f'{[t.value for t in VolumeType]}') from None
        return cls(
            name=cfg.get('name'),
            type=vt,
            cloud=cfg.get('cloud', 'gcp'),
            region=cfg.get('region'),
            zone=cfg.get('zone'),
            size_gb=parse_size_gb(cfg.get('size')),
            use_existing=bool(cfg.get('use_existing', False)),
            config=dict(cfg.get('config') or {}),
        )

    def mount_command(self, dst: str) -> str:
        """Shell command mounting this volume at `dst` on a host. All
        interpolated paths are shell-quoted — mount paths and hostpath
        sources are user input and reach `rm -rf`."""
        import shlex
        from skypilot_tpu.data import mounting_utils
        q_dst = shlex.quote(dst)
        if self.type == VolumeType.GCSFUSE:
            return mounting_utils.gcs_mount_command(
                self.config['bucket'], dst,
                only_dir=self.config.get('sub_path', ''))
        if self.type == VolumeType.HOSTPATH:
            q_src = shlex.quote(self.config['path'])
            return (f'mkdir -p {q_dst} && '
                    f'[ "$(readlink -f {q_src})" = '
                    f'"$(readlink -f {q_dst})" ] '
                    f'|| (mkdir -p {q_src} && rm -rf {q_dst} && '
                    f'ln -sfn {q_src} {q_dst})')
        if self.type == VolumeType.K8S_PVC:
            # The PVC is already mounted into the pod by the StatefulSet
            # spec (render_slice pvc_volumes) at /mnt/<name>; link the
            # task's requested path onto it.
            q_src = shlex.quote(f'/mnt/{self.name}')
            return (f'mkdir -p "$(dirname {q_dst})" && '
                    f'[ "$(readlink -f {q_src})" = '
                    f'"$(readlink -f {q_dst})" ] '
                    f'|| (rm -rf {q_dst} && ln -sfn {q_src} {q_dst})')
        if self.type == VolumeType.GCP_PD:
            dev = shlex.quote(f'/dev/disk/by-id/google-{self.name}')
            return (f'sudo mkdir -p {q_dst} && '
                    f'(sudo blkid {dev} >/dev/null 2>&1 || '
                    f'sudo mkfs.ext4 -q {dev}) && '
                    f'sudo mount -o discard,defaults {dev} {q_dst} && '
                    f'sudo chmod a+w {q_dst}')
        raise exceptions.InvalidTaskError(f'Unknown volume type {self.type}')
