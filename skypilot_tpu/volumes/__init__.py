"""Volumes: persistent storage attachable to clusters (reference
``sky/volumes/``: Volume model volume.py:25, server ops server/core.py)."""
from skypilot_tpu.volumes.core import (volume_apply, volume_delete,
                                       volume_list, volume_refresh)
from skypilot_tpu.volumes.volume import Volume, VolumeType

__all__ = ['Volume', 'VolumeType', 'volume_apply', 'volume_delete',
           'volume_list', 'volume_refresh']
